"""Unit tests for the tunnel-recovery watcher's banking logic
(device_watcher.py) and the device-phase lock in bench.py.

The watcher exists to bank on-chip bench results in any window the
tunneled TPU allows (VERDICT r4 next-step #2); these tests pin the
invariants that make a catch durable: ok results are never clobbered
by later errors/skips, completeness is judged per-bench, and the lock
protocol can't lose mutual exclusion to a dead holder's leftovers.
"""
import importlib.util
import json
import os
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def dw(tmp_path_factory):
    spec = importlib.util.spec_from_file_location(
        "device_watcher", os.path.join(REPO, "device_watcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def bank_paths(dw, tmp_path, monkeypatch):
    monkeypatch.setattr(dw, "BANK", str(tmp_path / "bank.json"))
    monkeypatch.setattr(dw, "RUN_SCRATCH", str(tmp_path / "run.json"))
    return dw


def test_bench_list_is_shared_with_bench_py(dw):
    import bench
    assert dw.BENCHES is bench.DEVICE_BENCHES
    assert len(dw.BENCHES) == 10


def test_bench_of_classifies_real_phase_keys(dw):
    # exact key names bench._run_device_phase emits on success
    cases = {
        "tpu_merge_git_makefile_ops_per_sec": "tpu_merge_git_makefile",
        "tpu_merge_git_makefile_prep_ms": "tpu_merge_git_makefile",
        "tpu_merge_git_makefile_docs_per_call": "tpu_merge_git_makefile",
        "tpu_merge_git_makefile_pallas_ops_per_sec":
            "tpu_merge_git_makefile_pallas",
        "tpu_merge_git_makefile_pallas_per_call_ms":
            "tpu_merge_git_makefile_pallas",
        "tpu_zone_git_makefile_ops_per_sec": "tpu_zone_git_makefile",
        "tpu_zone_friendsforever_prep_ms": "tpu_zone_friendsforever",
        "tpu_merge_friendsforever_per_call_ms": "tpu_merge_friendsforever",
        "tpu_merge_node_nodecc_best_ops_per_sec":
            "tpu_merge_node_nodecc_sweep",
        "tpu_merge_node_nodecc_best_chunk": "tpu_merge_node_nodecc_sweep",
        "tpu_merge_batch_sweep": "tpu_merge_node_nodecc_sweep",
        "tpu_session_per_merge_ms": "tpu_session_friendsforever",
        "tpu_session_batch32_ms": "tpu_session_friendsforever",
        "tpu_session_build_ms": "tpu_session_friendsforever",
        "tpu_batched_replay_ops_per_sec": "tpu_batched_replay",
        "fanin_10k_propagation_ms": "fanin_10k",
        "tpu_transform_git_makefile_ops_per_sec":
            "tpu_transform_git_makefile",
        "tpu_transform_speedup": "tpu_transform_git_makefile",
        "tpu_transform_device_plan_ms": "tpu_transform_git_makefile",
        "tpu_transform_host_plan_ms": "tpu_transform_git_makefile",
        # globals
        "device_platform": None,
        "tunnel_rtt_ms": None,
    }
    for key, bench_name in cases.items():
        assert dw._bench_of(key) == bench_name, key
    # every bench's error key maps back to it
    for b in dw.BENCHES:
        assert dw._bench_of(f"{b}_error") == b


def test_merge_never_downgrades_ok_data(dw):
    run1 = {"tpu_session_per_merge_ms": 4.3,
            "tpu_merge_node_nodecc_best_ops_per_sec": 9e6,
            "tpu_merge_git_makefile_ops_per_sec": 6e6,
            "fanin_10k_error": "wedge"}
    run2 = {"tpu_session_friendsforever_error": "wedge",
            "tpu_merge_node_nodecc_sweep_error": "wedge",
            "tpu_merge_git_makefile_error": "wedge",
            "tpu_merge_git_makefile_pallas_ops_per_sec": 3e6,
            "fanin_10k_propagation_ms": 67.0}
    m = dw._merge_summary(dw._merge_summary({}, run1), run2)
    # earlier oks survive later errors (including non-prefix key benches)
    assert m["tpu_session_per_merge_ms"] == 4.3
    assert "tpu_session_friendsforever_error" not in m
    assert m["tpu_merge_node_nodecc_best_ops_per_sec"] == 9e6
    assert m["tpu_merge_git_makefile_ops_per_sec"] == 6e6
    assert "tpu_merge_git_makefile_error" not in m
    # later ok evicts earlier error; pallas does not mask its base bench
    assert m["fanin_10k_propagation_ms"] == 67.0
    assert "fanin_10k_error" not in m
    assert m["tpu_merge_git_makefile_pallas_ops_per_sec"] == 3e6


def test_merge_discards_skip_errors(dw):
    banked = {"tpu_batched_replay_ops_per_sec": 1e6}
    m = dw._merge_summary(
        banked, {"tpu_batched_replay_error":
                 "skipped: already banked this round"})
    assert m == banked


def test_catch_complete_requires_every_bench(dw):
    partial = {"tpu_merge_git_makefile_ops_per_sec": 1.0,
               "fanin_10k_propagation_ms": 1.0}
    assert not dw._catch_complete(partial)
    # real ok-key spellings, one per bench
    done = {"tpu_merge_git_makefile_ops_per_sec": 1,
            "tpu_merge_git_makefile_pallas_ops_per_sec": 1,
            "tpu_merge_friendsforever_ops_per_sec": 1,
            "tpu_merge_node_nodecc_best_ops_per_sec": 1,
            "tpu_zone_git_makefile_ops_per_sec": 1,
            "tpu_zone_friendsforever_ops_per_sec": 1,
            "tpu_session_per_merge_ms": 1,
            "tpu_transform_git_makefile_ops_per_sec": 1,
            "tpu_batched_replay_ops_per_sec": 1,
            "fanin_10k_propagation_ms": 1}
    assert dw._catch_complete(done)
    assert not dw._catch_complete({})


def test_bank_run_bounds_history_and_full_reports(bank_paths):
    dw = bank_paths
    m = dw._bank_run("t1", {"tpu_merge_git_makefile_ops_per_sec": 1e6,
                            "fanin_10k_error": "w"}, {"detail": 1})
    assert m["tpu_merge_git_makefile_ops_per_sec"] == 1e6
    # error-only run (globals present) stores no full report
    dw._bank_run("t2", {"device_platform": "tpu", "tunnel_rtt_ms": 9.0,
                        "fanin_10k_error": "w"}, {"big": "tail"})
    bank = json.load(open(dw.BANK))
    assert "full" in bank["runs"][0]
    assert "full" not in bank["runs"][1]
    for i in range(20):
        dw._bank_run(f"x{i}", {"fanin_10k_error": "w"}, {})
    assert len(json.load(open(dw.BANK))["runs"]) == 12
    # banked ok survives all those error runs
    assert json.load(open(dw.BANK))["summary"][
        "tpu_merge_git_makefile_ops_per_sec"] == 1e6


def test_bank_run_crash_fallback_reads_scratch(bank_paths):
    dw = bank_paths
    with open(dw.RUN_SCRATCH, "w") as f:
        json.dump({"summary": {"fanin_10k_propagation_ms": 5.0},
                   "full": {}}, f)
    m = dw._bank_run("crash", None, None)
    assert m["fanin_10k_propagation_ms"] == 5.0


@pytest.fixture()
def lockdir(tmp_path, monkeypatch):
    import bench
    monkeypatch.setattr(bench, "DEVICE_LOCK", str(tmp_path / "lock"))
    return bench


def test_device_lock_roundtrip(lockdir):
    bench = lockdir
    bench._acquire_device_lock(timeout_s=5)
    assert int(open(bench.DEVICE_LOCK).read()) == os.getpid()
    bench._release_device_lock()
    assert not os.path.exists(bench.DEVICE_LOCK)


def test_device_lock_steals_dead_holder_fast(lockdir):
    bench = lockdir
    # a guaranteed-dead pid: fork a child that exits immediately, reap it
    pid = os.fork()
    if pid == 0:
        os._exit(0)
    os.waitpid(pid, 0)
    with open(bench.DEVICE_LOCK, "w") as f:
        f.write(str(pid))
    t0 = time.time()
    bench._acquire_device_lock(timeout_s=30)
    assert time.time() - t0 < 5
    bench._release_device_lock()


def test_device_lock_respects_live_holder(lockdir):
    bench = lockdir
    # a FOREIGN live pid (holder == own pid is treated as self/dead):
    # pid 1 is always alive
    with open(bench.DEVICE_LOCK, "w") as f:
        f.write("1")
    released = threading.Event()

    def free():
        time.sleep(2)
        os.remove(bench.DEVICE_LOCK)
        released.set()

    threading.Thread(target=free, daemon=True).start()
    t0 = time.time()
    bench._acquire_device_lock(timeout_s=60)
    assert released.is_set() and time.time() - t0 >= 1.5
    bench._release_device_lock()


def test_release_leaves_foreign_lock(lockdir):
    bench = lockdir
    with open(bench.DEVICE_LOCK, "w") as f:
        f.write("424242")
    bench._release_device_lock()
    assert os.path.exists(bench.DEVICE_LOCK)


def test_phase_skip_runs_no_subprocess(lockdir, monkeypatch):
    """With every bench skipped and a caller-supplied ok probe, the phase
    must return instantly with 9 short skip errors and no device work.
    (DT_DEVICE_BANK points into the empty tmp dir so the REPO's real
    bank cannot substitute results into this isolated run.)"""
    bench = lockdir
    monkeypatch.setenv("DT_DEVICE_BANK",
                       os.path.dirname(bench.DEVICE_LOCK) + "/no_bank.json")
    full = {}
    t0 = time.time()
    out = bench._run_device_phase(
        full, probe={"ok": True, "platform": "cpu", "rtt_ms": 1.0},
        skip=frozenset(bench.DEVICE_BENCHES))
    assert time.time() - t0 < 2.0
    errs = {k: v for k, v in out.items() if k.endswith("_error")}
    assert len(errs) == len(bench.DEVICE_BENCHES)
    assert all("already banked" in v for v in errs.values())
    assert out["device_platform"] == "cpu"
    assert not os.path.exists(bench.DEVICE_LOCK)


def test_round_end_substitutes_banked_catches(lockdir, monkeypatch,
                                              tmp_path):
    """A bench that errors at round end but has a COMPLETE banked catch
    reports the banked numbers (VERDICT r4 #2 durability); partial
    catches substitute errors but keep their marker; live results are
    never overwritten."""
    import json as _json
    bench = lockdir
    bank = {"summary": {
        "tpu_merge_git_makefile_ops_per_sec": 8541360,
        "tpu_merge_git_makefile_per_call_ms": 326.71,
        "tpu_merge_node_nodecc_best_ops_per_sec": 6914401,
        "tpu_merge_node_nodecc_sweep_partial": "timed out at chunk 64",
        "fanin_10k_propagation_ms": 67.6,
    }, "runs": [{"label": "t", "at": time.time() - 3600}]}
    bp = tmp_path / "bank.json"
    bp.write_text(_json.dumps(bank))
    monkeypatch.setenv("DT_DEVICE_BANK", str(bp))

    out = {f"{b}_error": "device probe failed"
           for b in bench.DEVICE_BENCHES}
    full = {}
    merged = bench._substitute_banked(dict(out), full)
    assert merged["tpu_merge_git_makefile_ops_per_sec"] == 8541360
    assert "tpu_merge_git_makefile_error" not in merged
    # partial catch: substituted WITH its marker
    assert merged["tpu_merge_node_nodecc_best_ops_per_sec"] == 6914401
    assert "sweep_partial" in str(sorted(merged))
    # benches with no banked data keep their errors
    assert "tpu_zone_git_makefile_error" in merged
    assert "tpu_merge_git_makefile" in merged["device_bank_used"]["benches"]
    assert merged["device_bank_used"]["at"]
    assert full["device_bank_used"]

    # a live full result is never replaced by the bank
    live = {"tpu_merge_git_makefile_ops_per_sec": 111}
    m2 = bench._substitute_banked(dict(live), {})
    assert m2["tpu_merge_git_makefile_ops_per_sec"] == 111

    # a STALE bank (previous round's committed file) never substitutes
    bank["runs"][0]["at"] = time.time() - 30 * 3600
    bp.write_text(_json.dumps(bank))
    m3 = bench._substitute_banked(dict(out), {})
    assert "device_bank_used" not in m3
    assert "tpu_merge_git_makefile_error" in m3


def test_partial_results_bank_but_stay_retryable(dw):
    """A sweep that timed out / crashed mid-curve banks its completed
    points (marked `_partial`), is NOT counted complete, is retried
    (not in the skip set), and is replaced by a later full run — while
    never downgrading an existing full result."""
    partial = {"tpu_merge_node_nodecc_best_ops_per_sec": 5e6,
               "tpu_merge_node_nodecc_best_chunk": 8,
               "tpu_merge_node_nodecc_sweep_partial": "timed out at 64"}
    m = dw._merge_summary({}, partial)
    assert m["tpu_merge_node_nodecc_best_ops_per_sec"] == 5e6
    per, _ = dw._group(m)
    b = "tpu_merge_node_nodecc_sweep"
    # the partial marker classifies to its bench and blocks completeness
    assert dw._bench_of("tpu_merge_node_nodecc_sweep_partial") == b
    assert dw._bench_ok(per[b]) and not dw._bench_full_ok(per[b])
    assert not dw._catch_complete({**m,
        **{f"{x}_ok": 1 for x in dw.BENCHES if x != b}})

    # partial beats error, later partial beats earlier partial
    m2 = dw._merge_summary({"tpu_merge_node_nodecc_sweep_error": "wedge"},
                           partial)
    assert "tpu_merge_node_nodecc_sweep_error" not in m2
    later = {"tpu_merge_node_nodecc_best_ops_per_sec": 6e6,
             "tpu_merge_node_nodecc_sweep_partial": "crash at 1024"}
    m3 = dw._merge_summary(m, later)
    assert m3["tpu_merge_node_nodecc_best_ops_per_sec"] == 6e6

    # a full run replaces the partial AND clears the marker
    full = {"tpu_merge_node_nodecc_best_ops_per_sec": 9e6,
            "tpu_merge_node_nodecc_best_chunk": 1024}
    m4 = dw._merge_summary(m, full)
    assert m4["tpu_merge_node_nodecc_best_ops_per_sec"] == 9e6
    assert "tpu_merge_node_nodecc_sweep_partial" not in m4
    per4, _ = dw._group(m4)
    assert dw._bench_full_ok(per4[b])

    # and a later PARTIAL never downgrades a banked full result
    m5 = dw._merge_summary(m4, partial)
    assert m5["tpu_merge_node_nodecc_best_ops_per_sec"] == 9e6
    assert "tpu_merge_node_nodecc_sweep_partial" not in m5
