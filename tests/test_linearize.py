"""Device linearizer (listmerge_tpu) — exactness against the native tracker.

The Fugue-tree linearization (diamond_types_tpu/tpu/linearize.py) must
reproduce the sequential YjsMod integrate order (reference:
src/listmerge/merge.rs:154-278) ITEM-FOR-ITEM, and the device checkout
(tpu/merge_kernel.py) must produce byte-identical documents.
"""

import random

import numpy as np
import pytest

from diamond_types_tpu.encoding.decode import decode_into, load_oplog
from diamond_types_tpu.encoding.encode import encode_oplog
from diamond_types_tpu.text.crdt import ListCRDT
from diamond_types_tpu.native.core import NativeContext, native_available
from diamond_types_tpu.tpu.linearize import (UNDERWATER, build_tree_np,
                                             fugue_linearize_jax,
                                             fugue_order_np,
                                             split_runs_at_anchors)
from diamond_types_tpu.tpu.merge_kernel import (_agent_keys, checkout_device,
                                                checkout_batch_device,
                                                prepare_doc)

from conftest import reference_path

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native core unavailable")


def _tracker_table(oplog):
    ctx = NativeContext(oplog)
    ctx.transform([], [int(x) for x in oplog.version])
    return ctx.dump_tracker(keep_underwater=True)


def _expand(ids, length):
    length = np.where(ids >= UNDERWATER, 1, length)
    return np.concatenate([np.arange(i, i + l)
                           for i, l in zip(ids, length)])


def _fuzz_oplog(seed, steps=20):
    rng = random.Random(seed)
    base = ListCRDT()
    a = base.get_or_create_agent_id("root")
    base.insert(a, 0, "".join(rng.choice("abcd") for _ in range(60)))
    data = encode_oplog(base.oplog)
    peers = []
    for nm in ["p0", "p1", "p2"]:
        c = ListCRDT()
        decode_into(c.oplog, data)
        c.branch = c.oplog.checkout_tip()
        peers.append((c, c.get_or_create_agent_id(nm)))
    for _ in range(steps):
        c, agn = peers[rng.randrange(3)]
        doc_len = len(c.branch.snapshot())
        if doc_len > 20 and rng.random() < 0.4:
            p = rng.randrange(0, doc_len - 8)
            c.delete(agn, p, p + rng.randrange(1, 8))
        else:
            p = rng.randrange(0, doc_len + 1)
            c.insert(agn, p, "".join(rng.choice("WXYZ")
                                     for _ in range(rng.randrange(1, 6))))
    c0 = peers[0][0]
    for d in [encode_oplog(c.oplog) for c, _ in peers]:
        decode_into(c0.oplog, d)
    return c0.oplog


def _order_matches_tracker(oplog):
    ids, ln, ol, orr, st, ev = _tracker_table(oplog)
    if len(ids) == 0:
        return True
    s_ids, s_len, s_ol, s_orr = split_runs_at_anchors(ids, ln, ol, orr)
    ag, sq = _agent_keys(oplog, s_ids)
    perm = fugue_order_np(s_ids, s_len, s_ol, s_orr, ag, sq)
    truth = _expand(ids, ln)
    mine = _expand(s_ids[perm], s_len[perm])
    return len(truth) == len(mine) and bool((truth == mine).all())


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt",
                                    "node_nodecc.dt"])
def test_order_matches_tracker_corpora(corpus):
    ol = load_oplog(open(reference_path("benchmark_data", corpus),
                         "rb").read())
    assert _order_matches_tracker(ol)


@pytest.mark.parametrize("seed", range(8))
def test_order_matches_tracker_fuzz(seed):
    assert _order_matches_tracker(_fuzz_oplog(seed))


def test_jax_matches_numpy_reference():
    ol = load_oplog(open(reference_path("benchmark_data",
                                        "friendsforever.dt"), "rb").read())
    ids, ln, olg, orr, st, ev = _tracker_table(ol)
    s_ids, s_len, s_ol, s_orr = split_runs_at_anchors(ids, ln, olg, orr)
    ag, sq = _agent_keys(ol, s_ids)
    perm_np = fugue_order_np(s_ids, s_len, s_ol, s_orr, ag, sq)
    parent, side, ka, ks = build_tree_np(s_ids, s_len, s_ol, s_orr, ag, sq)
    import jax
    import jax.numpy as jnp
    perm_jax = np.asarray(jax.jit(fugue_linearize_jax)(
        jnp.asarray(parent), jnp.asarray(side),
        jnp.asarray(ka), jnp.asarray(ks)))
    assert (perm_np == perm_jax).all()


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt",
                                    "node_nodecc.dt"])
def test_device_checkout_corpora(corpus):
    ol = load_oplog(open(reference_path("benchmark_data", corpus),
                         "rb").read())
    assert checkout_device(ol) == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(6))
def test_device_checkout_fuzz(seed):
    ol = _fuzz_oplog(seed)
    assert checkout_device(ol) == ol.checkout_tip().snapshot()


def test_device_checkout_batched():
    oplogs = [_fuzz_oplog(s) for s in range(5)]
    texts = checkout_batch_device([prepare_doc(o) for o in oplogs])
    for t, o in zip(texts, oplogs):
        assert t == o.checkout_tip().snapshot()


def test_device_checkout_linear_doc():
    lin = ListCRDT()
    a = lin.get_or_create_agent_id("solo")
    lin.insert(a, 0, "hello world")
    lin.delete(a, 2, 5)
    assert checkout_device(lin.oplog) == lin.oplog.checkout_tip().snapshot()


def test_device_checkout_empty_doc():
    empty = ListCRDT()
    assert checkout_device(empty.oplog) == ""
