"""Device linearizer (listmerge_tpu) — exactness against the native tracker.

The Fugue-tree linearization (diamond_types_tpu/tpu/linearize.py) must
reproduce the sequential YjsMod integrate order (reference:
src/listmerge/merge.rs:154-278) ITEM-FOR-ITEM, and the device checkout
(tpu/merge_kernel.py) must produce byte-identical documents.
"""

import random

import numpy as np
import pytest

from diamond_types_tpu.encoding.decode import decode_into, load_oplog
from diamond_types_tpu.encoding.encode import encode_oplog
from diamond_types_tpu.text.crdt import ListCRDT
from diamond_types_tpu.native.core import NativeContext, native_available
from diamond_types_tpu.tpu.linearize import (UNDERWATER, build_tree_np,
                                             fugue_linearize_jax,
                                             fugue_order_np,
                                             resolve_pos_keys,
                                             split_runs_at_anchors)
from diamond_types_tpu.tpu.merge_kernel import (_agent_keys, checkout_device,
                                                checkout_batch_device,
                                                prepare_doc)

from conftest import reference_path

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native core unavailable")


def _tracker_table(oplog):
    ctx = NativeContext(oplog)
    ctx.transform([], [int(x) for x in oplog.version])
    return ctx.dump_tracker(keep_underwater=True)


def _expand(ids, length):
    length = np.where(ids >= UNDERWATER, 1, length)
    return np.concatenate([np.arange(i, i + l)
                           for i, l in zip(ids, length)])


def _fuzz_oplog(seed, steps=20, cross_sync=False):
    """Random concurrent history over 3+ peers.

    With cross_sync=True, peers exchange encoded oplogs MID-RUN and new
    peers spawn from stale snapshots — so items' origins can themselves be
    tie-broken concurrent inserts (the class that triggered the round-1
    sibling-order divergence; ADVICE.md finding #2)."""
    rng = random.Random(seed)
    base = ListCRDT()
    a = base.get_or_create_agent_id("root")
    base.insert(a, 0, "".join(rng.choice("abcd") for _ in range(60)))
    data = encode_oplog(base.oplog)
    peers = []

    def spawn(nm, data):
        c = ListCRDT()
        decode_into(c.oplog, data)
        c.branch = c.oplog.checkout_tip()
        peers.append((c, c.get_or_create_agent_id(nm)))

    for nm in ["p0", "p1", "p2"]:
        spawn(nm, data)
    for _ in range(steps):
        i = rng.randrange(len(peers))
        c, agn = peers[i]
        doc_len = len(c.branch.snapshot())
        if doc_len > 20 and rng.random() < 0.4:
            p = rng.randrange(0, doc_len - 8)
            c.delete(agn, p, p + rng.randrange(1, 8))
        else:
            p = rng.randrange(0, doc_len + 1)
            c.insert(agn, p, "".join(rng.choice("WXYZ")
                                     for _ in range(rng.randrange(1, 6))))
        if cross_sync and rng.random() < 0.35:
            j = rng.randrange(len(peers))
            if j != i:
                cj = peers[j][0]
                decode_into(cj.oplog, encode_oplog(c.oplog))
                cj.branch = cj.oplog.checkout_tip()
        if cross_sync and len(peers) < 6 and rng.random() < 0.15:
            # a peer joining from a stale snapshot of another peer
            src = peers[rng.randrange(len(peers))][0]
            spawn(f"q{len(peers)}", encode_oplog(src.oplog))
    c0 = peers[0][0]
    for d in [encode_oplog(c.oplog) for c, _ in peers]:
        decode_into(c0.oplog, d)
    return c0.oplog


def _advisor_repro_oplog():
    """ADVICE.md round-1 high-severity repro: same-(parent, side) siblings
    with different right origins. base 'WY'; a/b concurrently insert P/X
    between W and Y (tie-break puts P first); d (sees all) inserts '1'
    between P and X (ol=P, orr=X); e (sees only P's branch) inserts '2'
    between P and Y (ol=P, orr=Y). YjsMod orders '2' before '1' (right
    origin Y is FARTHER right than X): 'WP21XY'."""
    base = ListCRDT()
    r = base.get_or_create_agent_id("root")
    base.insert(r, 0, "WY")
    d0 = encode_oplog(base.oplog)

    def peer(name, *patches):
        c = ListCRDT()
        for p in (d0,) + patches:
            decode_into(c.oplog, p)
        c.branch = c.oplog.checkout_tip()
        return c, c.get_or_create_agent_id(name)

    pa, a = peer("a")
    pa.insert(a, 1, "P")
    da = encode_oplog(pa.oplog)
    pb, b = peer("b")
    pb.insert(b, 1, "X")
    db = encode_oplog(pb.oplog)
    pd, d = peer("d", da, db)
    assert pd.branch.snapshot() == "WPXY"
    pd.insert(d, 2, "1")
    pe, e = peer("e", da)
    assert pe.branch.snapshot() == "WPY"
    pe.insert(e, 2, "2")
    final = ListCRDT()
    for p in (d0, da, db, encode_oplog(pd.oplog), encode_oplog(pe.oplog)):
        decode_into(final.oplog, p)
    return final.oplog


def _order_matches_tracker(oplog):
    ids, ln, ol, orr, st, ev = _tracker_table(oplog)
    if len(ids) == 0:
        return True
    s_ids, s_len, s_ol, s_orr = split_runs_at_anchors(ids, ln, ol, orr)
    ag, sq = _agent_keys(oplog, s_ids)
    perm = fugue_order_np(s_ids, s_len, s_ol, s_orr, ag, sq)
    truth = _expand(ids, ln)
    mine = _expand(s_ids[perm], s_len[perm])
    return len(truth) == len(mine) and bool((truth == mine).all())


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt",
                                    "node_nodecc.dt"])
def test_order_matches_tracker_corpora(corpus):
    ol = load_oplog(open(reference_path("benchmark_data", corpus),
                         "rb").read())
    assert _order_matches_tracker(ol)


@pytest.mark.parametrize("seed", range(8))
def test_order_matches_tracker_fuzz(seed):
    assert _order_matches_tracker(_fuzz_oplog(seed))


@pytest.mark.parametrize("seed", range(30))
def test_order_matches_tracker_cross_sync_fuzz(seed):
    assert _order_matches_tracker(
        _fuzz_oplog(seed, steps=30, cross_sync=True))


def test_sibling_order_right_origin_rule():
    """Round-1 ADVICE high-severity regression: YjsMod orders same-gap
    siblings by right-origin position DESCENDING before the agent key."""
    ol = _advisor_repro_oplog()
    host = ol.checkout_tip().snapshot()
    assert host == "WP21XY"
    assert _order_matches_tracker(ol)
    assert checkout_device(ol) == host


def test_jax_matches_numpy_reference():
    ol = load_oplog(open(reference_path("benchmark_data",
                                        "friendsforever.dt"), "rb").read())
    ids, ln, olg, orr, st, ev = _tracker_table(ol)
    s_ids, s_len, s_ol, s_orr = split_runs_at_anchors(ids, ln, olg, orr)
    ag, sq = _agent_keys(ol, s_ids)
    perm_np = fugue_order_np(s_ids, s_len, s_ol, s_orr, ag, sq)
    parent, side, ka, ks, orr_run = build_tree_np(s_ids, s_len, s_ol, s_orr,
                                                  ag, sq)
    kp = resolve_pos_keys(parent, side, ka, ks, orr_run)
    import jax
    import jax.numpy as jnp
    perm_jax = np.asarray(jax.jit(fugue_linearize_jax)(
        jnp.asarray(parent), jnp.asarray(side), jnp.asarray(kp),
        jnp.asarray(ka), jnp.asarray(ks)))
    assert (perm_np == perm_jax).all()


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt",
                                    "node_nodecc.dt"])
def test_device_checkout_corpora(corpus):
    ol = load_oplog(open(reference_path("benchmark_data", corpus),
                         "rb").read())
    assert checkout_device(ol) == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(6))
def test_device_checkout_fuzz(seed):
    ol = _fuzz_oplog(seed)
    assert checkout_device(ol) == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(8))
def test_device_checkout_cross_sync_fuzz(seed):
    ol = _fuzz_oplog(seed + 100, steps=30, cross_sync=True)
    assert checkout_device(ol) == ol.checkout_tip().snapshot()


def test_device_checkout_batched():
    oplogs = [_fuzz_oplog(s) for s in range(5)]
    texts = checkout_batch_device([prepare_doc(o) for o in oplogs])
    for t, o in zip(texts, oplogs):
        assert t == o.checkout_tip().snapshot()


def _random_frontier(rng, oplog):
    """A valid random frontier: dominators of a random LV sample."""
    n = len(oplog)
    k = rng.randrange(1, 4)
    lvs = [rng.randrange(n) for _ in range(k)]
    return [int(x) for x in oplog.cg.graph.find_dominators(lvs)]


@pytest.mark.parametrize("seed", range(10))
def test_device_incremental_merge_fuzz(seed):
    """merge_device from an arbitrary frontier == host Branch.merge
    (VERDICT r1 missing #2: the device path must serve incremental merge,
    not only full checkout). Reference: TransformedOpsIter::new(from, ...)
    merge.rs:618."""
    from diamond_types_tpu.tpu.merge_kernel import merge_device

    rng = random.Random(seed * 7919 + 13)
    ol = _fuzz_oplog(seed + 300, steps=25, cross_sync=True)
    for _ in range(4):
        frm = _random_frontier(rng, ol)
        mrg = (_random_frontier(rng, ol) if rng.random() < 0.5
               else [int(x) for x in ol.version])
        b = ol.checkout(frm)
        b.merge(ol, mrg)
        text, frontier = merge_device(ol, frm, mrg)
        assert text == b.snapshot()
        assert sorted(frontier) == sorted(int(x) for x in b.version)


def test_device_merge_branch_backend(monkeypatch):
    """DT_TPU_DEVICE_MERGE=1 routes Branch.merge through the device."""
    monkeypatch.setenv("DT_TPU_DEVICE_MERGE", "1")
    ol = _fuzz_oplog(42, steps=20, cross_sync=True)
    b = ol.checkout([])
    b.merge(ol, ol.version)
    monkeypatch.delenv("DT_TPU_DEVICE_MERGE")
    assert b.snapshot() == ol.checkout_tip().snapshot()
    assert sorted(b.version) == sorted(int(x) for x in ol.version)


def test_device_checkout_linear_doc():
    lin = ListCRDT()
    a = lin.get_or_create_agent_id("solo")
    lin.insert(a, 0, "hello world")
    lin.delete(a, 2, 5)
    assert checkout_device(lin.oplog) == lin.oplog.checkout_tip().snapshot()


def test_device_checkout_empty_doc():
    empty = ListCRDT()
    assert checkout_device(empty.oplog) == ""


def test_materialize_matches_searchsorted_reference_incl_truncation():
    """The scatter+cummax run expansion must match the straightforward
    searchsorted formulation bit-for-bit, including cap < total (truncated
    materialization) and dead/empty runs."""
    import jax
    import jax.numpy as jnp

    from diamond_types_tpu.tpu.linearize import materialize_jax

    def reference(perm, vis_len, arena_off, arena, cap):
        vl = vis_len[perm]
        cum = jnp.cumsum(vl)
        total = cum[-1]
        starts = cum - vl
        j = jnp.arange(cap)
        r = jnp.searchsorted(cum, j, side="right")
        rc = jnp.clip(r, 0, vl.shape[0] - 1)
        src = arena_off[perm][rc] + (j - starts[rc])
        text = arena[jnp.clip(src, 0, arena.shape[0] - 1)]
        return jnp.where(j < total, text, 0), total

    n, caps = 32, (16, 64, 160)
    new_j = {c: jax.jit(lambda p, v, a, ar, c=c:
                        materialize_jax(p, v, a, ar, cap=c)) for c in caps}
    ref_j = {c: jax.jit(lambda p, v, a, ar, c=c:
                        reference(p, v, a, ar, c)) for c in caps}
    rng = np.random.RandomState(1)
    for _trial in range(60):
        perm = rng.permutation(n).astype(np.int32)
        vl = (rng.randint(0, 6, n) * (rng.random(n) < 0.7)).astype(np.int32)
        ao = rng.randint(0, 500, n).astype(np.int32)
        arena = rng.randint(1, 1000, 600).astype(np.int32)
        args = tuple(jnp.asarray(x) for x in (perm, vl, ao, arena))
        for cap in caps:
            a = new_j[cap](*args)
            b = ref_j[cap](*args)
            assert int(a[1]) == int(b[1])
            assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
