"""Writer groups (replicate/writergroup.py + the ReplicaNode wiring).

Two layers:

  * `WriterGroupTable` in isolation: install/refresh/drop semantics
    (floor fencing, replay guards), the floor-raise fence hook, and
    the crash-restart journal round-trip (entries restore EXPIRED,
    below-floor entries are not restored at all);
  * a live 3-server mesh: promotion runs a real quorum round and
    re-keys the leader's lease, members install the grant with their
    fencing floor raised and admit writes locally under the group
    epoch, a stale (superseded) grant is refused, a member that loses
    the leader self-fences to proxy-only, and demotion drains back to
    a single writer without losing the member's acked write.

The protocol's interleaving coverage lives in the model checker
(analysis/explore/ `writer-group` scenario + the `demote-without-
drain` / `promote-floor-drop` seeded mutations, tests/test_explore.py);
these tests pin the concrete object behavior those runs rely on.
"""

import threading
import time
import urllib.request

import pytest

from diamond_types_tpu.replicate import (FaultInjector, ReplicaJournal,
                                         attach_replication)
from diamond_types_tpu.replicate.writergroup import WriterGroupTable

pytestmark = pytest.mark.writergroup


# ---- helpers -------------------------------------------------------------

def _mesh(n, faults=None, **opts):
    from diamond_types_tpu.tools.server import serve
    opts.setdefault("backoff_base_s", 0.01)
    opts.setdefault("backoff_cap_s", 0.05)
    opts.setdefault("lease_ttl_s", 30.0)
    httpds, addrs = [], []
    for _ in range(n):
        httpd = serve(port=0, serve_shards=1)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            faults=faults, **opts))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def _teardown(httpds):
    for h in httpds:
        h.shutdown()
        h.server_close()


def _step(nodes, rounds=1):
    for _ in range(rounds):
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            n.antientropy.run_round()


def _promote(nodes, doc):
    """Acquire `doc`'s lease at its rendezvous owner and promote it to
    a 2-writer group with one healthy peer. Returns (leader, member)."""
    _step(nodes)
    leader = next(n for n in nodes
                  if n.desired_owner(doc) == n.self_id)
    assert leader.owns(doc)
    member = next(n for n in nodes if n is not leader)
    assert leader.promote_writer_group(doc, [member.self_id])
    return leader, member


# ---- WriterGroupTable unit ----------------------------------------------

def test_install_fences_and_replays():
    t = WriterGroupTable("hostB", ttl_s=60.0)
    assert t.install("d", 5, ["hostA", "hostB"], "hostA", floor=5)
    assert t.get("d").epoch == 5
    assert t.get("d").quorum_size() == 2
    # below the caller's floor: a replayed grant from a superseded
    # group must not resurrect it
    assert not t.install("d", 4, ["hostA", "hostB"], "hostA", floor=5)
    # an older grant never clobbers a newer registration
    assert t.install("d", 7, ["hostA", "hostB"], "hostA", floor=5)
    assert not t.install("d", 6, ["hostA", "hostB"], "hostA", floor=5)
    assert t.get("d").epoch == 7
    # idempotent re-install at the current epoch = renewal
    assert t.install("d", 7, ["hostA", "hostB"], "hostA", floor=5)


def test_drop_at_or_below_guards_replayed_demotes():
    t = WriterGroupTable("hostB", ttl_s=60.0)
    t.install("d", 7, ["hostA", "hostB"], "hostA", floor=0)
    # a demote fencing epoch 5 must not drop the NEWER group at 7
    assert not t.drop("d", at_or_below=5)
    assert t.get("d") is not None
    assert t.drop("d", at_or_below=7)
    assert t.get("d") is None
    assert not t.drop("d")                      # idempotent


def test_fence_below_is_the_floor_raise_hook():
    t = WriterGroupTable("hostB", ttl_s=60.0)
    t.install("d", 7, ["hostA", "hostB"], "hostA", floor=0)
    t.fence_below("d", 7)                       # floor == epoch: keeps
    assert t.get("d") is not None
    t.fence_below("d", 8)                       # floor passed it: drops
    assert t.get("d") is None


def test_journal_round_trip_restores_expired_and_skips_fenced(tmp_path):
    """Crash-restart: registrations survive via the replica journal,
    come back EXPIRED (accepting again takes a renewal through the
    leader), and entries below the restored fencing floor are gone —
    their group was superseded while we were down."""
    prefix = str(tmp_path / "rj")
    j = ReplicaJournal(prefix)
    t = WriterGroupTable("hostB", ttl_s=60.0)
    t.journal = j
    t.install("d", 7, ["hostA", "hostB"], "hostA", floor=0)
    t.install("e", 3, ["hostA", "hostB"], "hostA", floor=0)
    t.install("gone", 2, ["hostA", "hostB"], "hostA", floor=0)
    t.drop("gone")
    # crash: no close() — reopen replays the WAL
    j2 = ReplicaJournal(prefix)
    assert set(j2.restored_groups()) == {"d", "e"}
    t2 = WriterGroupTable("hostB", ttl_s=60.0)
    # the floor passed e's epoch while we were down
    assert t2.restore(j2, {"d": 0, "e": 5}.get) == 1
    assert t2.get("e") is None
    g = t2.get("d")
    assert g.epoch == 7 and g.members == ("hostA", "hostB")
    # restored EXPIRED: the entry exists but cannot admit
    assert t2.clock() >= g.expires_at
    # a restore-then-renewal round trip re-arms it
    assert not t2.refresh("d", 6)               # wrong epoch refused
    assert t2.refresh("d", 7)
    assert t2.clock() < t2.get("d").expires_at
    j2.close()


# ---- live mesh -----------------------------------------------------------

def test_promotion_runs_quorum_and_rekeys_lease():
    httpds, nodes, addrs = _mesh(3)
    try:
        doc = "wg-promote"
        _step(nodes)
        leader = next(n for n in nodes
                      if n.desired_owner(doc) == n.self_id)
        assert leader.owns(doc)
        e0 = leader.leases.active_epoch(doc)
        member = next(n for n in nodes if n is not leader)

        # a refused quorum round refuses the promotion outright
        real = leader._run_quorum
        leader._run_quorum = lambda d, e, t: False
        assert not leader.promote_writer_group(doc, [member.self_id])
        assert leader.writergroups.get(doc) is None
        assert leader.leases.active_epoch(doc) == e0
        leader._run_quorum = real

        assert leader.promote_writer_group(doc, [member.self_id])
        g = leader.writergroups.get(doc)
        assert g.leader == leader.self_id
        assert set(g.members) == {leader.self_id, member.self_id}
        # the lease was re-keyed to the ratified group epoch
        assert g.epoch > e0
        assert leader.leases.active_epoch(doc) == g.epoch
        # the member installed the grant with its floor raised to it
        gm = member.writergroups.get(doc)
        assert gm is not None and gm.epoch == g.epoch
        assert member.leases.max_epoch_of(doc) >= g.epoch
        # ...and admits locally, stamped with the group epoch
        assert member.group_accepts(doc)
        assert member.owns(doc)
        assert member.active_epoch(doc) == g.epoch
        assert member.metrics.get("writergroup", "member_admits") == 1
    finally:
        _teardown(httpds)


def test_stale_grant_refused_after_demotion():
    httpds, nodes, addrs = _mesh(3)
    try:
        doc = "wg-stale"
        leader, member = _promote(nodes, doc)
        old = leader.writergroups.get(doc).epoch
        assert leader.can_demote(doc)           # all members healthy
        assert leader.demote_writer_group(doc)
        assert leader.writergroups.get(doc) is None
        # the demotion epoch fenced the member (floor > group epoch)
        assert member.writergroups.get(doc) is None
        assert member.leases.max_epoch_of(doc) > old
        assert not member.group_accepts(doc)
        # a replayed grant from the superseded group is refused
        rejected0 = member.metrics.get("writergroup",
                                       "stale_installs_rejected")
        assert not member.writergroups.install(
            doc, old, [leader.self_id, member.self_id],
            leader.self_id, floor=member.leases.max_epoch_of(doc))
        # ...including over the wire
        resp = member.leases  # silence lint on unused locals
        out = leader.table.call_json(
            member.self_id, "/replicate/lease",
            {"action": "group", "doc": doc, "epoch": old,
             "members": [leader.self_id, member.self_id],
             "leader": leader.self_id, "ttl_s": 30.0})
        assert out["ok"] is False
        assert member.metrics.get(
            "writergroup", "stale_installs_rejected") > rejected0
        assert resp.max_epoch_of(doc) > old
    finally:
        _teardown(httpds)


def test_member_self_fences_on_group_quorum_loss():
    faults = FaultInjector(seed=3)
    httpds, nodes, addrs = _mesh(3, faults=faults, group_ttl_s=1.0)
    try:
        doc = "wg-fence"
        leader, member = _promote(nodes, doc)
        assert member.group_accepts(doc)
        # cut the member off from the leader (both directions): in a
        # 2-writer group the leader IS the quorum, so the member must
        # degrade to proxy-only immediately — no operator action
        faults.partition(member.self_id, leader.self_id)
        for _ in range(4):
            member.table.probe_once()
        assert not member.table.is_healthy(leader.self_id)
        assert not member.group_accepts(doc)
        assert not member.owns(doc)             # proxy-only now
        # the maintain loop then drops the expired registration (the
        # renewal path is cut), completing the self-fence
        deadline = member.clock() + 3 * member.writergroups.ttl_s
        while member.clock() < deadline \
                and member.writergroups.get(doc) is not None:
            member.maintain()
            time.sleep(0.02)
        assert member.writergroups.get(doc) is None
        assert member.metrics.get("writergroup", "self_fenced") >= 1
    finally:
        _teardown(httpds)


def test_demote_drains_member_write_back_to_single_writer():
    httpds, nodes, addrs = _mesh(3)
    try:
        doc = "wg-drain"
        leader, member = _promote(nodes, doc)
        # the member ACCEPTS a write locally under the group epoch
        body = (b'{"agent": "wg-agent", "version": [], "ops": '
                b'[{"kind": "ins", "pos": 0, "text": "member-write "}]}')
        req = urllib.request.Request(
            f"http://{member.self_id}/doc/{doc}/edit", data=body)
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
        assert member.metrics.get("writergroup", "member_admits") >= 1
        # demotion drains the group back to one writer...
        assert leader.demote_writer_group(doc)
        assert leader.writergroups.get(doc) is None
        assert member.writergroups.get(doc) is None
        assert leader.leases.active_epoch(doc) > 0
        assert not member.group_accepts(doc)
        # ...without losing the member's acked write: after
        # reconciliation every server shows it byte-identically
        _step(nodes, rounds=4)
        texts = set()
        for a in addrs:
            with urllib.request.urlopen(f"http://{a}/doc/{doc}",
                                        timeout=5) as r:
                texts.add(r.read().decode("utf8"))
        assert len(texts) == 1
        assert "member-write" in texts.pop()
    finally:
        _teardown(httpds)
