"""Live telemetry tests (obs/timeseries.py, slo.py, exemplars.py,
attrib.py + the serving-stack wiring): fake-clock window rollover, the
burn-rate alert state machine driven through every transition, the
exemplar -> trace round-trip via OpenMetrics, top-K sketch accuracy on
a Zipf workload, the disabled-path zero-allocation contract, the
/debug/slo + /debug/hot + /debug/events?since= endpoints, and the
seeded latency-injection acceptance run (flush-p99 SLO ok -> burning
-> ok, visible in /debug/slo, dt_slo_* gauges, and a failing
verdict). Tier-1 safe: in-process servers on ephemeral ports, no TPU.
"""

import json
import random
import threading
import tracemalloc
import urllib.error
import urllib.request
from collections import Counter

import pytest

from diamond_types_tpu.obs import Observability
from diamond_types_tpu.obs.attrib import HotAttribution, SpaceSaving
from diamond_types_tpu.obs.exemplars import ExemplarStore
from diamond_types_tpu.obs.hist import BOUNDS
from diamond_types_tpu.obs.prom import (CONTENT_TYPE,
                                        OPENMETRICS_CONTENT_TYPE,
                                        render_metrics)
from diamond_types_tpu.obs.recorder import FlightRecorder
from diamond_types_tpu.obs.slo import Objective, SloEngine
from diamond_types_tpu.obs.timeseries import TimeSeries, bucket_index

pytestmark = pytest.mark.telemetry


class _Clock:
    """Injectable monotonic clock for deterministic window math."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---- windowed time-series ------------------------------------------------

def test_timeseries_rate_and_fake_clock_rollover():
    clk = _Clock()
    ts = TimeSeries(window_s=10.0, n_windows=6, clock=clk)
    for _ in range(30):
        ts.inc("serve.admitted")
    assert ts.rate("serve.admitted", 10.0) == pytest.approx(3.0)
    # a wider horizon spreads the same events over more seconds
    assert ts.rate("serve.admitted", 60.0) == pytest.approx(0.5)
    clk.t = 25.0
    # two windows later the events are out of the 10s horizon but
    # still inside the 60s one
    assert ts.rate("serve.admitted", 10.0) == 0.0
    assert ts.rate("serve.admitted", 60.0) == pytest.approx(0.5)
    # past the whole ring: everything aged out
    clk.t = 65.0
    assert ts.rate("serve.admitted", 60.0) == 0.0
    # ring slot reuse: writing at window index 6 lands in slot 0 and
    # must reset the stale window, not add to it
    ts.inc("serve.admitted", 5)
    assert ts.rate("serve.admitted", 10.0) == pytest.approx(0.5)
    assert ts.recorded == 31


def test_timeseries_hist_rollover_and_quantile_brackets():
    clk = _Clock()
    ts = TimeSeries(window_s=10.0, n_windows=60, clock=clk)
    rng = random.Random(7)
    vals = [rng.choice([1e-5, 1e-4, 1e-3, 1e-2, 0.1])
            * rng.uniform(1.0, 2.0) for _ in range(2000)]
    for v in vals:
        ts.observe("serve.flush", v)
    vals.sort()
    for q in (0.5, 0.9, 0.99):
        true = vals[min(int(q * len(vals)), len(vals) - 1)]
        got = ts.quantile("serve.flush", q, 300.0)
        assert true / 2 <= got <= true * 2, (q, true, got)
    # rate counts hist observations too
    assert ts.rate("serve.flush", 60.0) == pytest.approx(2000 / 60.0)
    # everything rolls out past the horizon
    clk.t = 400.0
    assert ts.quantile("serve.flush", 0.99, 300.0) == 0.0
    assert ts.rate("serve.flush", 300.0) == 0.0


def test_timeseries_count_over_threshold_semantics():
    ts = TimeSeries(window_s=10.0, n_windows=8, clock=_Clock())
    for _ in range(8):
        ts.observe("serve.flush", 0.001)
    for _ in range(2):
        ts.observe("serve.flush", 10.0)
    bad, total = ts.count_over("serve.flush", 0.1, 300.0)
    assert (bad, total) == (2, 10)
    # a value exactly on a bucket bound is GOOD for a threshold on
    # that bound (le is upper-inclusive, matching hist.py)
    ts2 = TimeSeries(window_s=10.0, n_windows=8, clock=_Clock())
    b = BOUNDS[10]
    ts2.observe("x", b)
    assert ts2.count_over("x", b, 300.0) == (0, 1)
    assert bucket_index(b) == 10
    # sum_over folds counters and latency sums
    ts2.inc("y", 4.0)
    assert ts2.sum_over("y", 300.0) == pytest.approx(4.0)
    assert ts2.sum_over("x", 300.0) == pytest.approx(b)


def test_timeseries_snapshot_shape():
    ts = TimeSeries(window_s=10.0, n_windows=8, clock=_Clock())
    ts.inc("serve.admitted", 6)
    ts.observe("serve.flush", 0.02)
    snap = ts.snapshot()
    assert snap["version"] == 1 and snap["enabled"]
    assert snap["recorded"] == 2
    row = snap["series"]["serve.admitted"]
    assert row["rate_60s"] == pytest.approx(0.1)
    assert snap["series"]["serve.flush"]["p99_300s"] > 0
    json.dumps(snap)   # JSON-able for /metrics


# ---- zero-allocation disabled paths --------------------------------------

def test_disabled_telemetry_single_branch_zero_alloc():
    """The disabled live tier is ONE branch per call: tracemalloc must
    attribute zero allocations to timeseries/exemplars/attrib across
    200 record cycles (mirrors the obs/trace.py pin)."""
    import diamond_types_tpu.obs.attrib as at_mod
    import diamond_types_tpu.obs.exemplars as ex_mod
    import diamond_types_tpu.obs.timeseries as ts_mod
    ts = TimeSeries(enabled=False)
    ex = ExemplarStore(enabled=False)
    at = HotAttribution(enabled=False)
    # touch everything once before measuring
    ts.inc("w")
    ts.observe("w", 0.1)
    ex.note("w", 0.1, "ab")
    at.note("ops", doc="d", agent="a")
    files = {ts_mod.__file__, ex_mod.__file__, at_mod.__file__}

    def _cycle():
        for _ in range(200):
            ts.inc("serve.admitted")
            ts.observe("serve.flush", 0.01)
            ex.note("serve.flush", 0.01, "abcd")
            at.note("ops", doc="d1", agent="a1")

    # Interpreter artifacts can masquerade as growth: function-entry
    # frame objects are occasionally malloc'd fresh (empty freelist) and
    # attributed to the `def` line of these files, and lineno-0 rows are
    # module bookkeeping. Warm one full loop, filter to real source
    # lines, and retry a bounded number of times — a genuine per-call
    # leak in the disabled path fails every attempt with count ~200.
    _cycle()
    grew = []
    tracemalloc.start()
    for _attempt in range(3):
        before = tracemalloc.take_snapshot()
        _cycle()
        after = tracemalloc.take_snapshot()
        grew = [st for st in after.compare_to(before, "lineno")
                if st.size_diff > 0
                and st.traceback[0].filename in files
                and st.traceback[0].lineno > 0]
        if not grew:
            break
    tracemalloc.stop()
    assert not grew, [str(g) for g in grew]
    assert ts.recorded == 0 and ex.noted == 0 and at.noted == 0


def test_observability_telemetry_toggle():
    """`telemetry=False` (the bench A/B control arm) disables the live
    tier while the cumulative tier keeps working, and the SLO verdict
    trivially passes."""
    obs = Observability(sample_rate=1.0, telemetry=False)
    assert not obs.ts.enabled
    assert not obs.exemplars.enabled and not obs.attrib.enabled
    obs.ts.observe("serve.flush", 99.0)
    v = obs.slo.verdict()
    assert v["slo_ok"] and not v["burning"]
    snap = obs.snapshot()
    assert snap["timeseries"]["enabled"] is False
    assert snap["slo"]["enabled"] is False
    # the cumulative tier is untouched by the toggle
    obs.tracer.start("t").end()
    assert obs.tracer.stats()["started"] >= 1


# ---- burn-rate state machine ---------------------------------------------

def _tight_objective(**kw):
    base = dict(name="flush_p99", series="serve.flush",
                threshold_s=0.1, target=0.99,
                fast_window_s=60.0, slow_window_s=300.0)
    base.update(kw)
    return Objective(**base)


def test_burn_rate_transition_matrix():
    """ok -> warning -> burning -> ok through seeded latencies on a
    fake clock, with every transition recorded for /debug/events."""
    clk = _Clock()
    ts = TimeSeries(window_s=10.0, n_windows=60, clock=clk)
    rec = FlightRecorder(capacity=32)
    eng = SloEngine(ts, objectives=[_tight_objective()], recorder=rec)

    def state():
        return eng.evaluate()[0]["state"]

    # ok: plenty of traffic, all under threshold
    for _ in range(100):
        ts.observe("serve.flush", 0.005)
    assert state() == "ok"
    # warning: ~2% bad -> burn ~2 (>= 1.0) on both horizons, but the
    # fast page threshold (14.4) is not met
    for _ in range(2):
        ts.observe("serve.flush", 1.0)
    assert state() == "warning"
    # burning: ~23% bad -> fast burn ~23 >= 14.4 AND slow ~23 >= 6
    for _ in range(28):
        ts.observe("serve.flush", 1.0)
    assert state() == "burning"
    # back to ok once the bad windows age past the slow horizon
    clk.t = 400.0
    for _ in range(50):
        ts.observe("serve.flush", 0.005)
    assert state() == "ok"
    al = eng.snapshot()
    assert al["objectives"][0]["transitions"] == 3
    kinds = [e for e in rec.dump() if e["kind"] == "slo_transition"]
    assert [(e["frm"], e["to"]) for e in kinds] == \
        [("ok", "warning"), ("warning", "burning"), ("burning", "ok")]


def test_burn_rate_fast_blip_without_slow_budget_is_warning():
    """The fast AND slow conjunction suppresses one-window blips: a
    100%-bad fast window over a mostly-good slow horizon pages
    nothing."""
    clk = _Clock()
    ts = TimeSeries(window_s=10.0, n_windows=60, clock=clk)
    eng = SloEngine(ts, objectives=[_tight_objective()])
    for _ in range(400):                      # good history at t=0
        ts.observe("serve.flush", 0.005)
    clk.t = 250.0                             # inside slow, past fast
    for _ in range(20):                       # a fully-bad fast window
        ts.observe("serve.flush", 1.0)
    row = eng.evaluate()[0]
    assert row["fast"]["burn"] >= 14.4
    assert row["slow"]["burn"] < 6.0
    assert row["state"] == "warning"


def test_slo_empty_series_is_ok_and_verdict_shape():
    eng = SloEngine(TimeSeries(clock=_Clock()))
    snap = eng.snapshot()
    assert snap["ok"] and snap["by_state"]["burning"] == 0
    assert {r["state"] for r in snap["objectives"]} == {"ok"}
    v = eng.verdict()
    assert v == {"slo_ok": True, "burning": [], "warning": []}


# ---- exemplars -----------------------------------------------------------

def test_exemplar_trace_roundtrip_openmetrics():
    """An exemplar noted against a sampled span must come back out of
    the OpenMetrics exposition on the right `le` bucket line, carrying
    a trace id that resolves to a buffered span."""
    from diamond_types_tpu.serve.metrics import ServeMetrics
    obs = Observability(sample_rate=1.0)
    sm = ServeMetrics(2, flush_docs=4, max_pending=64)
    sm.ts = obs.ts
    span = obs.tracer.start("serve.flush")
    tid = span.context().trace_id
    dur = 0.003
    sm.record_flush(0, 2, 5, "size", dur_s=dur)
    obs.exemplars.note("serve.flush", dur, tid)
    span.end()
    # store-level round trip
    fam = obs.exemplars.for_family("serve.flush")
    le = BOUNDS[bucket_index(dur)]
    assert fam[le]["trace"] == tid
    assert fam[le]["value"] == pytest.approx(dur)
    # exposition round trip (OM only)
    doc = {"serve": sm.snapshot(), "obs": obs.snapshot()}
    om = render_metrics(doc, openmetrics=True)
    lines = [ln for ln in om.splitlines()
             if ln.startswith("dt_flush_latency_seconds_bucket")
             and f'trace_id="{tid}"' in ln]
    assert len(lines) == 1, om
    assert f'le="{le!r}"' in lines[0]
    assert om.rstrip().endswith("# EOF")
    # OM counter TYPE lines drop _total; samples keep it
    for ln in om.splitlines():
        if ln.startswith("# TYPE") and ln.endswith(" counter"):
            assert not ln.split()[2].endswith("_total"), ln
    assert "dt_serve_flushed_ops_total 5" in om
    # classic exposition: no exemplars, no EOF, _total TYPEs intact
    classic = render_metrics(doc)
    assert "trace_id=" not in classic
    assert "# EOF" not in classic
    assert "# TYPE dt_serve_flushed_ops_total counter" in classic
    # the trace id resolves to a real buffered span
    assert tid in {s["trace"] for s in obs.tracer.spans()}


def test_exemplar_overflow_bucket_is_inf():
    ex = ExemplarStore()
    ex.note("serve.flush", 1e9, "aa")        # beyond the last bound
    snap = ex.snapshot()
    assert snap["families"]["serve.flush"][0]["le"] == "+Inf"
    assert snap["noted"] == 1


# ---- top-K attribution ---------------------------------------------------

def test_space_saving_vs_exact_on_zipf():
    """Sketch guarantees on a Zipf workload: every key with true count
    > total/k is tracked, and every reported count brackets truth
    within its error bound."""
    rng = random.Random(42)
    n_keys, n_events, k = 500, 20000, 64
    weights = [1.0 / (i + 1) ** 1.2 for i in range(n_keys)]
    events = rng.choices(range(n_keys), weights=weights, k=n_events)
    sk = SpaceSaving(k)
    exact = Counter()
    for e in events:
        key = f"doc{e:03d}"
        sk.offer(key)
        exact[key] += 1
    assert sk.total == n_events
    assert len(sk.counts) == k
    for key, true in exact.items():
        if true > n_events / k:
            assert key in sk.counts, key
    for key, cnt, err in sk.top(10):
        true = exact[key]
        assert true <= cnt <= true + err + 1e-9, (key, true, cnt, err)
    # the true heavy hitters rank at the top
    reported = [key for key, _, _ in sk.top(10)]
    for key, _ in exact.most_common(3):
        assert key in reported


def test_hot_attribution_dims_kinds_and_prom():
    at = HotAttribution(k=8)
    at.note("ops", doc="d1", agent="alice", n=5)
    at.note("ops", doc="d2", n=1)
    at.note("bytes", doc="d1", n=1024)
    at.note("device_s", doc="d1", n=0.25)
    at.note("cache_misses", doc="d2")
    at.note("ops", n=3)          # no doc/agent: counted nowhere
    snap = at.snapshot(top=5)
    assert snap["doc"]["ops"]["top"][0][0] == "d1"
    assert snap["doc"]["bytes"]["total"] == pytest.approx(1024)
    assert snap["agent"]["ops"]["top"][0][:2] == ["alice", 5]
    assert snap["doc"]["cache_misses"]["tracked"] == 1
    text = render_metrics({"obs": {"hot": snap}})
    assert ('dt_hot_top{dim="doc",key="d1",kind="ops"} 5' in text)
    assert ('dt_hot_attributed_total{dim="doc",kind="bytes"} 1024'
            in text)


# ---- double-write choke points -------------------------------------------

def test_metrics_double_write_into_timeseries():
    """Every record_* choke point in serve/read/replicate metrics
    lands its live twin in the shared TimeSeries under the canonical
    family names the SLO objectives read."""
    from diamond_types_tpu.read.metrics import ReadMetrics
    from diamond_types_tpu.replicate.metrics import ReplicationMetrics
    from diamond_types_tpu.serve.metrics import ServeMetrics
    ts = TimeSeries(clock=_Clock())
    sm = ServeMetrics(2, flush_docs=4, max_pending=64)
    sm.ts = ts
    sm.bump(0, "submits")
    sm.record_flush(0, 2, 5, "size", dur_s=0.003)
    sm.observe_queue_wait(0.02)
    sm.record_hydration("prefetches")
    sm.observe_cold_start(0.01)
    rm = ReadMetrics()
    rm.ts = ts
    rm.bump("reads")
    rm.observe_staleness(0.1)
    rm.observe_wait(0.01)
    pm = ReplicationMetrics()
    pm.ts = ts
    pm.bump("quorum", "acks", 3)
    pm.observe_latency("quorum_round", 0.2)
    want = {"serve.submits", "serve.flush", "serve.flushed_ops",
            "serve.queue_wait", "serve.hydration.prefetches",
            "serve.hydration_cold_start", "read.reads",
            "read.staleness", "read.read_wait", "repl.quorum.acks",
            "repl.quorum_round"}
    assert want <= set(ts.names())
    # the SLO objective series specifically
    assert ts.count_over("serve.flush", 30.0, 300.0) == (0, 1)
    assert ts.quantile("serve.queue_wait", 0.99, 300.0) > 0
    # the cumulative tier recorded too (double-write, not a move)
    snap = sm.snapshot()
    assert snap["latencies"]["queue_wait"]["count"] == 1
    assert snap["version"] == 13


# ---- zero-fill satellite -------------------------------------------------

def test_prom_zero_fills_read_and_hydration_families():
    """A fresh server with zero traffic (and no read tier at all)
    still exposes the full dt_read_* / dt_serve_hydration_* families
    so dashboards never see series flicker into existence."""
    from diamond_types_tpu.read.metrics import READ_KEYS
    from diamond_types_tpu.serve.metrics import HYDRATION_KEYS, \
        ServeMetrics
    sm = ServeMetrics(2, flush_docs=4, max_pending=64)
    text = render_metrics({"serve": sm.snapshot()})
    for key in READ_KEYS:
        assert f"dt_read_{key}_total 0" in text, key
    for key in HYDRATION_KEYS:
        assert f"dt_serve_hydration_{key}_total 0" in text, key
    assert "dt_read_local_ratio 0.0" in text
    assert "dt_read_staleness_seconds_count 0" in text
    assert "dt_read_wait_latency_seconds_count 0" in text
    assert "dt_queue_wait_latency_seconds_count 0" in text


# ---- server endpoints ----------------------------------------------------

def _serve_one(**obs_opts):
    from diamond_types_tpu.tools.server import serve
    opts = {"sample_rate": 0.0}
    opts.update(obs_opts)
    httpd = serve(port=0, obs_opts=opts)
    addr = f"127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, addr


def _get_json(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return json.loads(r.read())


def test_debug_events_since_cursor():
    httpd, addr = _serve_one()
    try:
        rec = httpd.store.obs.recorder
        rec.record("ev_a", i=1)
        rec.record("ev_b", i=2)
        full = _get_json(addr, "/debug/events")
        assert len(full["events"]) == 2
        cursor = full["events"][-1]["seq"]
        inc = _get_json(addr, f"/debug/events?since={cursor}")
        assert inc["events"] == [] and inc["since"] == cursor
        rec.record("ev_c", i=3)
        inc = _get_json(addr, f"/debug/events?since={cursor}")
        assert [e["kind"] for e in inc["events"]] == ["ev_c"]
        assert inc["events"][0]["seq"] > cursor
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(addr, "/debug/events?since=nope")
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_openmetrics_content_negotiation():
    httpd, addr = _serve_one()
    try:
        # ?format=openmetrics forces OM 1.0
        with urllib.request.urlopen(
                f"http://{addr}/metrics?format=openmetrics",
                timeout=5) as r:
            assert r.headers["Content-Type"] == OPENMETRICS_CONTENT_TYPE
            assert r.headers["Cache-Control"] == "no-store"
            text = r.read().decode("utf8")
        assert text.rstrip().endswith("# EOF")
        # ?format=prom + an OpenMetrics Accept header negotiates up
        req = urllib.request.Request(
            f"http://{addr}/metrics?format=prom",
            headers={"Accept":
                     "application/openmetrics-text; version=1.0.0"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.headers["Content-Type"] == OPENMETRICS_CONTENT_TYPE
            assert r.read().decode("utf8").rstrip().endswith("# EOF")
        # plain ?format=prom stays classic (no EOF, classic ctype)
        with urllib.request.urlopen(
                f"http://{addr}/metrics?format=prom", timeout=5) as r:
            assert r.headers["Content-Type"] == CONTENT_TYPE
            assert "# EOF" not in r.read().decode("utf8")
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_slo_latency_injection_ok_burning_ok():
    """Acceptance: seeded latency injection drives the flush-p99 SLO
    ok -> burning -> ok, visible in GET /debug/slo, the dt_slo_*
    gauges, and a failing verdict (the block serve-bench and the soaks
    embed)."""
    httpd, addr = _serve_one(
        objectives=[_tight_objective()],
        ts_window_s=10.0, ts_windows=60)
    try:
        obs = httpd.store.obs
        clk = _Clock()
        obs.ts._clock = clk      # deterministic rollover
        obs.ts._t0 = 0.0
        # phase 1: healthy flush latencies -> ok everywhere
        for _ in range(200):
            obs.ts.observe("serve.flush", 0.005)
        snap = _get_json(addr, "/debug/slo")
        assert snap["ok"] is True
        assert snap["objectives"][0]["state"] == "ok"
        assert obs.slo.verdict()["slo_ok"] is True
        # phase 2: inject slow flushes -> burning, failing verdict
        for _ in range(60):
            obs.ts.observe("serve.flush", 1.0)
        snap = _get_json(addr, "/debug/slo")
        assert snap["ok"] is False
        row = snap["objectives"][0]
        assert row["state"] == "burning"
        assert row["fast"]["burn"] >= row["fast_burn_threshold"]
        with urllib.request.urlopen(
                f"http://{addr}/metrics?format=prom", timeout=5) as r:
            text = r.read().decode("utf8")
        assert 'dt_slo_state{objective="flush_p99"} 2' in text
        assert "dt_slo_ok 0" in text
        assert 'dt_slo_burn_rate{objective="flush_p99",window="fast"}' \
            in text
        v = obs.slo.verdict()
        assert v["slo_ok"] is False and v["burning"] == ["flush_p99"]
        # phase 3: the injected windows age out past the slow horizon
        clk.t = 400.0
        for _ in range(100):
            obs.ts.observe("serve.flush", 0.005)
        snap = _get_json(addr, "/debug/slo")
        assert snap["ok"] is True
        assert snap["objectives"][0]["state"] == "ok"
        assert snap["objectives"][0]["transitions"] >= 2
        # every transition hit the flight recorder for ?since= tails
        ev = _get_json(addr, "/debug/events")
        kinds = [e["to"] for e in ev["events"]
                 if e["kind"] == "slo_transition"]
        assert "burning" in kinds and "ok" in kinds
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_debug_hot_endpoint_and_obs_watch_cli(capsys):
    httpd, addr = _serve_one()
    try:
        obs = httpd.store.obs
        for _ in range(5):
            obs.attrib.note("ops", doc="hotdoc", agent="alice")
        obs.attrib.note("bytes", doc="hotdoc", n=2048)
        hot = _get_json(addr, "/debug/hot")
        assert hot["doc"]["ops"]["top"][0][0] == "hotdoc"
        assert hot["agent"]["ops"]["top"][0][0] == "alice"
        obs.ts.observe("serve.flush", 0.01)
        # the obs-watch CLI renders one round and exits 0 while no
        # objective burns
        from diamond_types_tpu.tools import cli
        rc = cli.main(["obs-watch", addr, "--rounds", "1",
                       "--interval", "0"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== slo ==" in out and "== hot docs ==" in out
        assert "hotdoc" in out
        assert "flush_p99" in out
    finally:
        httpd.shutdown()
        httpd.server_close()
