"""v1 binary format (.dt) decode tests against the shipped corpora
(reference: benchmark_data/*.dt; SURVEY.md §6)."""

import os

import pytest

from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.text.trace import load_trace
from tests.conftest import reference_path


def read(name):
    p = reference_path("benchmark_data", name)
    if not os.path.exists(p):
        pytest.skip(f"missing {p}")
    with open(p, "rb") as f:
        return f.read()


def test_friendsforever_parity_with_flat_trace():
    """The .dt concurrent oplog and the flattened linear trace must converge
    to the same document."""
    ol = load_oplog(read("friendsforever.dt"))
    flat = load_trace(reference_path("benchmark_data", "friendsforever_flat.json.gz"))
    assert ol.checkout_tip().snapshot() == flat.end_content


def test_git_makefile_decode_and_checkout():
    ol = load_oplog(read("git-makefile.dt"))
    assert len(ol) == 348819
    b = ol.checkout_tip()
    # High-fanout git DAG merges deterministically; content must be stable
    # across two independent checkouts.
    b2 = ol.checkout_tip()
    assert b.snapshot() == b2.snapshot()
    assert len(b) > 0


def test_decode_crc_validated():
    data = bytearray(read("friendsforever.dt"))
    data[100] ^= 0xFF
    from diamond_types_tpu.encoding.decode import ParseError
    with pytest.raises(ParseError):
        load_oplog(bytes(data))


def test_native_decoder_tables_identical_to_python():
    """The C++ fresh-load decoder must produce byte-identical oplog tables
    (op runs, graph, agent assignment, arenas) to the Python decoder on
    every shipped corpus."""
    import os

    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    from diamond_types_tpu.encoding.decode import load_oplog
    for name in ("friendsforever.dt", "git-makefile.dt", "node_nodecc.dt"):
        data = open(reference_path("benchmark_data", name), "rb").read()
        a = load_oplog(data)                       # native path
        os.environ["DT_TPU_NO_NATIVE"] = "1"
        try:
            b = load_oplog(data)                   # python path
        finally:
            del os.environ["DT_TPU_NO_NATIVE"]
        assert [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
                for r in a.ops.runs] == \
               [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
                for r in b.ops.runs], name
        assert a.cg.graph.starts == b.cg.graph.starts
        assert a.cg.graph.ends == b.cg.graph.ends
        assert a.cg.graph.parents == b.cg.graph.parents
        # the batch graph rebuild computes these three too — pin them
        # (a shadow/child regression would otherwise surface only as
        # wrong diff/dominator results much later)
        assert a.cg.graph.shadows == b.cg.graph.shadows
        assert a.cg.graph.child_idxs == b.cg.graph.child_idxs
        assert a.cg.graph.root_child_idxs == b.cg.graph.root_child_idxs
        assert a.cg.agent_assignment.global_runs == \
            b.cg.agent_assignment.global_runs
        assert a.cg.agent_assignment.agent_names == \
            b.cg.agent_assignment.agent_names
        assert a.version == b.version and a.doc_id == b.doc_id
        for kind in (0, 1):
            ar_a, ar_b = a.ops._arenas[kind], b.ops._arenas[kind]
            assert ar_a.get((0, len(ar_a))) == ar_b.get((0, len(ar_b)))


def test_native_decoder_rejects_corrupt_input():
    import os

    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    from diamond_types_tpu.encoding.decode import ParseError, load_oplog
    data = bytearray(
        open(reference_path("benchmark_data", "friendsforever.dt"),
             "rb").read())
    data[50] ^= 0xFF  # flip a byte: CRC must catch it
    with pytest.raises(ParseError):
        load_oplog(bytes(data))
    with pytest.raises(ParseError):
        load_oplog(b"NOTMAGIC" + bytes(data[8:]))


def test_native_decoder_fuzz_roundtrips():
    """encode -> native decode == original, across random oplogs (the
    encoder is Python; the native decoder must read everything it writes,
    including patch-content unknown runs and LZ4'd content)."""
    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
    from tests.test_encode import build_random_oplog, semantic_eq
    for seed in range(10):
        ol = build_random_oplog(seed, steps=40)
        data = encode_oplog(ol, ENCODE_FULL)
        ol2 = load_oplog(data)
        assert semantic_eq(ol, ol2), seed


def test_native_probe_failure_degrades_to_python(monkeypatch):
    """A broken native library (CDLL OSError, stale ABI AttributeError)
    must degrade the fresh-load fast path to the Python decoder, not break
    load_oplog (ADVICE r2). The failure is negative-cached."""
    from diamond_types_tpu.encoding import decode as dec
    from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
    from diamond_types_tpu.text.oplog import OpLog

    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    ol.add_insert(a, 0, "hello")
    data = encode_oplog(ol, ENCODE_FULL)

    calls = []

    def boom(_data):
        calls.append(1)
        raise OSError("simulated stale .so")

    import diamond_types_tpu.native.core as ncore
    monkeypatch.setattr(ncore, "decode_file_native", boom)
    monkeypatch.setattr(dec, "_native_decode_ok", True)
    try:
        ol2 = dec.load_oplog(data)
        assert ol2.checkout_tip().snapshot() == "hello"
        ol3 = dec.load_oplog(data)  # negative-cached: no second probe
        assert ol3.checkout_tip().snapshot() == "hello"
        assert len(calls) == 1
    finally:
        monkeypatch.setattr(dec, "_native_decode_ok", True)
