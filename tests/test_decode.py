"""v1 binary format (.dt) decode tests against the shipped corpora
(reference: benchmark_data/*.dt; SURVEY.md §6)."""

import os

import pytest

from diamond_types_tpu.encoding.decode import load_oplog
from diamond_types_tpu.text.trace import load_trace
from tests.conftest import reference_path


def read(name):
    p = reference_path("benchmark_data", name)
    if not os.path.exists(p):
        pytest.skip(f"missing {p}")
    with open(p, "rb") as f:
        return f.read()


def test_friendsforever_parity_with_flat_trace():
    """The .dt concurrent oplog and the flattened linear trace must converge
    to the same document."""
    ol = load_oplog(read("friendsforever.dt"))
    flat = load_trace(reference_path("benchmark_data", "friendsforever_flat.json.gz"))
    assert ol.checkout_tip().snapshot() == flat.end_content


def test_git_makefile_decode_and_checkout():
    ol = load_oplog(read("git-makefile.dt"))
    assert len(ol) == 348819
    b = ol.checkout_tip()
    # High-fanout git DAG merges deterministically; content must be stable
    # across two independent checkouts.
    b2 = ol.checkout_tip()
    assert b.snapshot() == b2.snapshot()
    assert len(b) > 0


def test_decode_crc_validated():
    data = bytearray(read("friendsforever.dt"))
    data[100] ^= 0xFF
    from diamond_types_tpu.encoding.decode import ParseError
    with pytest.raises(ParseError):
        load_oplog(bytes(data))
