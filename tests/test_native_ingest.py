"""Native local-ingest session parity (VERDICT r4 #3).

The session (native/dt_ingest.cpp + native/ingest.py) must build an
oplog BIT-identical to the per-op Python path — same RLE run structure,
same arenas, same encode bytes — for any linear local edit script, at
any flush cadence. Reference for the path being mirrored:
src/list/oplog.rs:203-296 (native local push), op_metrics.rs:235-271
(RLE append rules).
"""

import random

import pytest

from diamond_types_tpu.encoding.encode import encode_oplog
from diamond_types_tpu.native.ingest import native_ingest_available
from diamond_types_tpu.text.oplog import OpLog

pytestmark = pytest.mark.skipif(not native_ingest_available(),
                                reason="ingest extension unavailable")


def _run_python(script):
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    for op in script:
        if op[0] == "i":
            ol.add_insert(ag, op[1], op[2])
        elif op[0] == "d":
            ol.add_delete_without_content(ag, op[1], op[2])
        else:
            ol.add_delete_at(ag, ol.version, op[1], op[2], op[3])
    return ol


def _run_native(script, flush_every=None):
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    s = ol.local_session(ag)
    for k, op in enumerate(script):
        if op[0] == "i":
            s.insert(op[1], op[2])
        elif op[0] == "d":
            s.delete(op[1], op[2])
        else:
            s.delete(op[1], op[2], op[3])
        if flush_every and (k + 1) % flush_every == 0:
            s.flush()
    s.flush()
    return ol


def _assert_identical(a: OpLog, b: OpLog):
    assert len(a) == len(b)
    ra = [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
          for r in a.ops.runs]
    rb = [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
          for r in b.ops.runs]
    assert ra == rb
    assert encode_oplog(a) == encode_oplog(b)
    assert a.checkout_tip().snapshot() == b.checkout_tip().snapshot()


def _random_script(rng, n, alphabet="abcdef\U0001F600é"):
    doc = []
    script = []
    for _ in range(n):
        L = len(doc)
        r = rng.random()
        if r < 0.55 or L < 3:
            pos = rng.randrange(L + 1)
            txt = "".join(rng.choice(alphabet)
                          for _ in range(rng.randrange(1, 4)))
            script.append(("i", pos, txt))
            doc[pos:pos] = list(txt)
        else:
            st = rng.randrange(L - 1)
            en = st + rng.randrange(1, min(4, L - st) + 1)
            if r < 0.8:
                script.append(("d", st, en))
            else:
                script.append(("dc", st, en, "".join(doc[st:en])))
            del doc[st:en]
    return script, "".join(doc)


@pytest.mark.parametrize("flush_every", [None, 1, 7, 100])
def test_random_scripts_bit_identical(flush_every):
    rng = random.Random(20260730)
    script, end = _random_script(rng, 2500)
    a = _run_python(script)
    b = _run_native(script, flush_every)
    _assert_identical(a, b)
    assert b.checkout_tip().snapshot() == end


def test_seeded_boundary_backspace_then_delete_key():
    """The RLE cascade at a flush boundary: a backspace continuing the
    oplog's existing reverse run, then a delete-key op at the same
    position. The per-op path does NOT merge the delete-key op; an
    unseeded session would — the seed makes the decision against the
    true predecessor run."""
    script = [("i", 0, "abcdefgh"),
              ("d", 5, 7),    # fresh delete run
              ("d", 4, 5),    # backspace continuing it (reverse chain)
              ("d", 4, 5)]    # delete-key at the same position
    a = _run_python(script)
    # flush after every op so every merge crosses the seed boundary
    b = _run_native(script, flush_every=1)
    _assert_identical(a, b)


def test_typing_chain_merges_into_single_runs():
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    with ol.local_session(ag) as s:
        pos = 0
        for ch in "hello world":
            s.insert(pos, ch)
            pos += 1
    assert len(ol.ops.runs) == 1
    assert ol.checkout_tip().snapshot() == "hello world"
    # continuing the chain in a SECOND session must extend the same run
    with ol.local_session(ag) as s:
        s.insert(11, "!")
    assert len(ol.ops.runs) == 1
    assert ol.checkout_tip().snapshot() == "hello world!"


def test_lv_return_values_match_python_path():
    script = [("i", 0, "xyz"), ("d", 1, 2), ("i", 2, "qq")]
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    lvs_py = []
    for op in script:
        if op[0] == "i":
            lvs_py.append(ol.add_insert(ag, op[1], op[2]))
        else:
            lvs_py.append(ol.add_delete_without_content(ag, op[1], op[2]))
    ol2 = OpLog()
    ag2 = ol2.get_or_create_agent_id("t")
    lvs_nat = []
    with ol2.local_session(ag2) as s:
        for op in script:
            if op[0] == "i":
                lvs_nat.append(s.insert(op[1], op[2]))
            else:
                lvs_nat.append(s.delete(op[1], op[2]))
    assert lvs_py == lvs_nat


def test_bad_inputs_rejected():
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    s = ol.local_session(ag)
    with pytest.raises(ValueError):
        s.insert(0, "")
    with pytest.raises(ValueError):
        s.delete(3, 3)
    s.insert(0, "abc")
    with pytest.raises(ValueError):
        s.delete(0, 2, "x")      # content length mismatch
    s.flush()
    assert ol.checkout_tip().snapshot() == "abc"


def test_noop_flush_after_external_edit_reseeds():
    """A flush with nothing pending is a no-op that re-seeds — an
    out-of-band oplog edit between flushes must not fail a clean
    context-manager exit."""
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    with ol.local_session(ag) as s:
        s.insert(0, "a")
        s.flush()
        ol.add_insert(ag, 0, "b")
        # and a SECOND batch after re-seeding still lands correctly
        s.flush()
        s.insert(0, "c")
    assert ol.checkout_tip().snapshot() == "cba"


def test_mutation_during_session_detected():
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    ol.add_insert(ag, 0, "base")
    s = ol.local_session(ag)
    s.insert(4, "x")
    ol.add_insert(ag, 0, "sneaky")   # out-of-band mutation
    with pytest.raises(RuntimeError):
        s.flush()
    # the check fires BEFORE drain: pending edits survive the failure
    assert s.pending() == 1


def test_bom_and_lone_surrogate_round_trip():
    """UTF-32 decode at drain must not sniff a leading U+FEFF as a BOM
    (it would silently shorten the arena) and must pass lone surrogates
    through like the pure-Python str arenas do. (Checkout of surrogate
    content is limited the same way on BOTH paths — the native context
    rejects it at sync, and the server rejects it at the edge — so
    parity is asserted on the stored state, not the checkout.)"""
    ol = OpLog()
    ag = ol.get_or_create_agent_id("t")
    with ol.local_session(ag) as s:
        s.insert(0, "﻿BOM")
        s.insert(4, "a\ud800b")
    ol2 = OpLog()
    ag2 = ol2.get_or_create_agent_id("t")
    ol2.add_insert(ag2, 0, "﻿BOM")
    ol2.add_insert(ag2, 4, "a\ud800b")
    assert ol.ops.get_run_content(ol.ops.runs[0]) == "﻿BOMa\ud800b" \
        == ol2.ops.get_run_content(ol2.ops.runs[0])
    assert [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
            for r in ol.ops.runs] == \
           [(r.lv, r.kind, r.start, r.end, r.fwd, r.content_pos)
            for r in ol2.ops.runs]


def test_kill_switch_falls_back_to_python_session(tmp_path):
    """DT_TPU_NO_NATIVE must make local_session() genuinely native-free
    (same kill switch every native fast path honors)."""
    import subprocess
    import sys
    code = """
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.native.ingest import PySession
ol = OpLog(); ag = ol.get_or_create_agent_id("t")
s = ol.local_session(ag)
assert isinstance(s, PySession), type(s)
with s:
    s.insert(0, "fallback")
    s.delete(0, 1, "f")
assert ol.checkout_tip().snapshot() == "allback"
print("OK")
"""
    import os
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=dict(os.environ, DT_TPU_NO_NATIVE="1"))
    assert r.returncode == 0 and "OK" in r.stdout, r.stderr[-500:]


def test_trace_replay_native_matches_per_op():
    from diamond_types_tpu.text.trace import (load_trace, replay_into_oplog,
                                              replay_into_oplog_native)
    data = load_trace(
        "/root/reference/benchmark_data/sveltecomponent.json.gz")
    a = replay_into_oplog(data)
    b = replay_into_oplog_native(data)
    _assert_identical(a, b)
    assert b.checkout_tip().snapshot() == data.end_content
