"""Encode/decode round-trip tests (reference: src/list/encoding/fuzzer.rs,
tests.rs — encode -> decode -> semantic equality)."""

import random

import pytest

from diamond_types_tpu import OpLog
from diamond_types_tpu.encoding.decode import decode_into, load_oplog
from diamond_types_tpu.encoding.encode import (ENCODE_FULL, ENCODE_PATCH,
                                               encode_oplog)
from tests.conftest import reference_path
from tests.test_fuzz import random_edit


def semantic_eq(a: OpLog, b: OpLog) -> bool:
    """Oplogs equal modulo agent-id permutation (reference: src/list/eq.rs)."""
    if len(a) != len(b):
        return False
    va = a.cg.local_to_remote_frontier(a.cg.version)
    vb = b.cg.local_to_remote_frontier(b.cg.version)
    if sorted(va) != sorted(vb):
        return False
    return a.checkout_tip().snapshot() == b.checkout_tip().snapshot()


def build_random_oplog(seed, steps=40):
    rng = random.Random(seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("alice", "bob")]
    branches = [([], "")]
    for _ in range(steps):
        bi = rng.randrange(len(branches))
        v, c = branches[bi]
        v, c = random_edit(rng, ol, agents[rng.randrange(2)], v, c)
        branches[bi] = (v, c)
        if rng.random() < 0.25 and len(branches) < 3:
            branches.append(branches[bi])
        if rng.random() < 0.2 and len(branches) >= 2:
            i, j = rng.sample(range(len(branches)), 2)
            mv = ol.cg.graph.version_union(branches[i][0], branches[j][0])
            branches[i] = (mv, ol.checkout(mv).snapshot())
    return ol


@pytest.mark.parametrize("seed", range(15))
def test_roundtrip_random(seed):
    ol = build_random_oplog(seed)
    data = encode_oplog(ol, ENCODE_FULL)
    ol2 = load_oplog(data)
    assert semantic_eq(ol, ol2)


def test_roundtrip_shipped_corpora():
    for name in ("friendsforever.dt", "git-makefile.dt"):
        with open(reference_path("benchmark_data", name), "rb") as f:
            ol = load_oplog(f.read())
        data = encode_oplog(ol, ENCODE_FULL)
        ol2 = load_oplog(data)
        assert ol.checkout_tip().snapshot() == ol2.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(10))
def test_patch_exchange(seed):
    """Peer A sends B only the ops B is missing (encode_from); B merges.
    (reference: encode_from/decode_and_add, SURVEY.md §3.5)."""
    ol = build_random_oplog(seed, steps=30)
    mid = ol.version  # snapshot version (copy)
    data_full = encode_oplog(ol, ENCODE_FULL)
    peer = load_oplog(data_full)
    assert semantic_eq(ol, peer)

    # ol advances further
    rng = random.Random(9999 + seed)
    v, c = list(mid), ol.checkout(mid).snapshot()
    for _ in range(10):
        v, c = random_edit(rng, ol, 0, v, c)

    # Send only the patch since `mid`.
    patch = encode_oplog(ol, ENCODE_PATCH, from_version=mid)
    assert len(patch) < len(encode_oplog(ol, ENCODE_FULL))
    decode_into(peer, patch)
    assert semantic_eq(ol, peer)


@pytest.mark.parametrize("seed", range(5))
def test_decode_is_idempotent(seed):
    ol = build_random_oplog(seed, steps=25)
    data = encode_oplog(ol, ENCODE_FULL)
    peer = load_oplog(data)
    n = len(peer)
    decode_into(peer, data)  # merging the same data again is a no-op
    assert len(peer) == n
    assert semantic_eq(ol, peer)
