"""Encode/decode round-trip tests (reference: src/list/encoding/fuzzer.rs,
tests.rs — encode -> decode -> semantic equality)."""

import random

import pytest

from diamond_types_tpu import OpLog
from diamond_types_tpu.encoding.decode import decode_into, load_oplog
from diamond_types_tpu.encoding.encode import (ENCODE_FULL, ENCODE_PATCH,
                                               encode_oplog)
from tests.conftest import reference_path
from tests.test_fuzz import random_edit


def semantic_eq(a: OpLog, b: OpLog) -> bool:
    """Oplogs equal modulo agent-id permutation (reference: src/list/eq.rs)."""
    if len(a) != len(b):
        return False
    va = a.cg.local_to_remote_frontier(a.cg.version)
    vb = b.cg.local_to_remote_frontier(b.cg.version)
    if sorted(va) != sorted(vb):
        return False
    return a.checkout_tip().snapshot() == b.checkout_tip().snapshot()


def build_random_oplog(seed, steps=40):
    rng = random.Random(seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("alice", "bob")]
    branches = [([], "")]
    for _ in range(steps):
        bi = rng.randrange(len(branches))
        v, c = branches[bi]
        v, c = random_edit(rng, ol, agents[rng.randrange(2)], v, c)
        branches[bi] = (v, c)
        if rng.random() < 0.25 and len(branches) < 3:
            branches.append(branches[bi])
        if rng.random() < 0.2 and len(branches) >= 2:
            i, j = rng.sample(range(len(branches)), 2)
            mv = ol.cg.graph.version_union(branches[i][0], branches[j][0])
            branches[i] = (mv, ol.checkout(mv).snapshot())
    return ol


@pytest.mark.parametrize("seed", range(15))
def test_roundtrip_random(seed):
    ol = build_random_oplog(seed)
    data = encode_oplog(ol, ENCODE_FULL)
    ol2 = load_oplog(data)
    assert semantic_eq(ol, ol2)


def test_roundtrip_shipped_corpora():
    for name in ("friendsforever.dt", "git-makefile.dt"):
        with open(reference_path("benchmark_data", name), "rb") as f:
            ol = load_oplog(f.read())
        data = encode_oplog(ol, ENCODE_FULL)
        ol2 = load_oplog(data)
        assert ol.checkout_tip().snapshot() == ol2.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(10))
def test_patch_exchange(seed):
    """Peer A sends B only the ops B is missing (encode_from); B merges.
    (reference: encode_from/decode_and_add, SURVEY.md §3.5)."""
    ol = build_random_oplog(seed, steps=30)
    mid = ol.version  # snapshot version (copy)
    data_full = encode_oplog(ol, ENCODE_FULL)
    peer = load_oplog(data_full)
    assert semantic_eq(ol, peer)

    # ol advances further
    rng = random.Random(9999 + seed)
    v, c = list(mid), ol.checkout(mid).snapshot()
    for _ in range(10):
        v, c = random_edit(rng, ol, 0, v, c)

    # Send only the patch since `mid`.
    patch = encode_oplog(ol, ENCODE_PATCH, from_version=mid)
    assert len(patch) < len(encode_oplog(ol, ENCODE_FULL))
    decode_into(peer, patch)
    assert semantic_eq(ol, peer)


@pytest.mark.parametrize("seed", range(5))
def test_decode_is_idempotent(seed):
    ol = build_random_oplog(seed, steps=25)
    data = encode_oplog(ol, ENCODE_FULL)
    peer = load_oplog(data)
    n = len(peer)
    decode_into(peer, data)  # merging the same data again is a no-op
    assert len(peer) == n
    assert semantic_eq(ol, peer)


def test_native_lz4_crc_byte_identical_to_python():
    """The native LZ4 compressor and CRC-32C must be byte-identical to the
    Python implementations — encoder output cannot depend on whether the
    native library is loaded."""
    import random as _r

    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    import diamond_types_tpu.native.core as nc
    from diamond_types_tpu.encoding import crc32c as C
    from diamond_types_tpu.encoding import lz4 as L
    rng = _r.Random(17)
    real_lz4, real_crc = nc.lz4_compress_native, nc.crc32c_native
    try:
        for _ in range(60):
            n = rng.randrange(0, 2500)
            alphabet = 4 if rng.random() < 0.5 else 256
            data = bytes(rng.randrange(alphabet) for _ in range(n))
            a = real_lz4(data)
            nc.lz4_compress_native = lambda d: None  # force python path
            b = L.lz4_compress_block(data)
            nc.lz4_compress_native = real_lz4
            assert a == b
            assert L.lz4_decompress_block(a, n) == data
            ac = real_crc(data)
            nc.crc32c_native = lambda d, s=0: None
            bc = C.crc32c(data)
            nc.crc32c_native = real_crc
            assert ac == bc
    finally:
        nc.lz4_compress_native = real_lz4
        nc.crc32c_native = real_crc


@pytest.mark.parametrize("corpus", ["friendsforever.dt", "git-makefile.dt",
                                    "node_nodecc.dt"])
def test_native_encoder_byte_identical(corpus):
    """The C++ writer (full snapshots AND patch encodes) must produce
    BYTE-identical output to the Python writer: its StWalk mirrors
    SpanningTreeWalker's traversal order exactly. This is the pin the
    encoder comments point at — callers may hash/dedup encoded blobs,
    so byte parity (not just semantic equality) is the contract."""
    import os
    from conftest import reference_path
    from diamond_types_tpu.encoding.encode import ENCODE_PATCH
    from diamond_types_tpu.native import native_available
    if not native_available() or os.environ.get("DT_TPU_NO_NATIVE"):
        pytest.skip("native library unavailable")
    with open(reference_path("benchmark_data", corpus), "rb") as f:
        ol = load_oplog(f.read())
    # a mid-history frontier: one LV per agent-ish — use the version of
    # a prefix checkout via the graph (take an LV near the middle)
    mid = [len(ol) // 2]
    cases = [
        ("full", lambda: encode_oplog(ol, ENCODE_FULL)),
        ("patch-root", lambda: encode_oplog(ol, ENCODE_PATCH,
                                            from_version=[])),
        ("patch-mid", lambda: encode_oplog(ol, ENCODE_PATCH,
                                           from_version=mid)),
    ]
    for label, enc in cases:
        nat_blob = enc()
        os.environ["DT_TPU_NO_NATIVE"] = "1"
        try:
            py_blob = enc()
        finally:
            del os.environ["DT_TPU_NO_NATIVE"]
        assert nat_blob == py_blob, f"{label}: native bytes != python"
    ol_nat = load_oplog(encode_oplog(ol, ENCODE_FULL))
    assert semantic_eq(ol_nat, ol)
    assert ol_nat.checkout_tip().snapshot() == ol.checkout_tip().snapshot()


@pytest.mark.parametrize("seed", range(8))
def test_native_encoder_random_oplogs(seed):
    """Random concurrent oplogs through the native writer round-trip."""
    import os
    from diamond_types_tpu.native import native_available
    if not native_available() or os.environ.get("DT_TPU_NO_NATIVE"):
        pytest.skip("native library unavailable")
    ol = build_random_oplog(seed, steps=60)
    blob = encode_oplog(ol, ENCODE_FULL)
    ol2 = load_oplog(blob)
    assert semantic_eq(ol2, ol)
    assert ol2.checkout_tip().snapshot() == ol.checkout_tip().snapshot()
