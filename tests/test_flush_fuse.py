"""Fused vmapped bucket flush (tpu/flush_fuse.py + serve/ wiring).

Covers the ISSUE-5 tentpole surface: kernel-level parity of the fused
replay against the host oracle on randomized mixed-size buckets, the
poisoned-length (-1) contract propagating through `sync_docs` into an
evict + host fallback, the per-shard flush worker pool genuinely
overlapping flush windows across shards (no process-global sync-lock
serialization), and the fencing recheck still running INSIDE the
worker. CPU-simulated devices via conftest's virtual 8-device mesh.
"""

import random
import threading
import time

import pytest

from diamond_types_tpu.serve.admission import PendingMerge
from diamond_types_tpu.serve.bank import SessionBank
from diamond_types_tpu.serve.metrics import ServeMetrics
from diamond_types_tpu.serve.scheduler import MergeScheduler
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.tpu import flush_fuse as ff

pytestmark = [pytest.mark.fused, pytest.mark.serve]

FUSED_OPTS = {"cap": 256, "max_ins": 4}


def _mk_oplog(doc_id: str) -> OpLog:
    ol = OpLog()
    ol.doc_id = doc_id
    return ol


def _random_edits(ol: OpLog, rng: random.Random, n: int,
                  agent: str = "a") -> None:
    """Mixed-size edits, including ops longer than max_ins (forcing the
    planner's chunk split) and deletes."""
    a = ol.get_or_create_agent_id(agent)
    for _ in range(n):
        cur = len(ol.checkout_tip().snapshot())
        if cur and rng.random() < 0.3:
            pos = rng.randrange(cur)
            end = min(pos + rng.randint(1, 9), cur)
            ol.add_delete_without_content(a, pos, end)
        else:
            pos = rng.randint(0, cur)
            s = "".join(rng.choice("abcdefgh") for _ in
                        range(rng.randint(1, 11)))
            ol.add_insert(a, pos, s)


def _items(doc_ids):
    return [PendingMerge(d, 1, 0.0) for d in doc_ids]


# ---- kernel-level parity -------------------------------------------------

def test_fused_replay_parity_randomized_mixed_buckets():
    """Fused whole-bucket replay == host checkout on randomized
    mixed-size docs, including concurrent two-agent histories."""
    rng = random.Random(11)
    ols = [_mk_oplog(f"d{i}") for i in range(5)]
    for i, ol in enumerate(ols):
        _random_edits(ol, rng, 2 + i)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for rnd in range(3):
        for i, ol in enumerate(ols):
            _random_edits(ol, rng, 1 + (i + rnd) % 3)
            if rnd == 1:
                # a concurrent branch from an old frontier — lands as
                # host-transformed positional ops
                b = ol.get_or_create_agent_id("b")
                ol.add_insert_at(b, [], 0, "Z" * (i + 1))
        plans = [s.plan_tail() for s in sess]
        fits = [p.fits(s.cap) for p, s in zip(plans, sess)]
        assert all(fits)
        ok, _dev = ff.fused_replay(sess, plans)
        assert all(ok)
        for s, ol in zip(sess, ols):
            assert s.text() == ol.checkout_tip().snapshot()


def test_fused_fn_per_doc_poison():
    """A bounded-shift contract violation poisons only ITS doc's
    length; bucket neighbors keep a valid result."""
    import jax.numpy as jnp
    import numpy as np
    fn = ff._fused_fn(2, 1, 2, 8)
    docs = jnp.zeros((2, 8), jnp.int32)
    lens = jnp.zeros((2,), jnp.int32)
    pos = jnp.zeros((2, 1), jnp.int32)
    dlen = jnp.zeros((2, 1), jnp.int32)
    # doc 0 violates (ilen 3 > max_ins 2); doc 1 inserts legally
    ilen = jnp.asarray([[3], [2]], jnp.int32)
    chars = jnp.full((2, 1, 2), ord("x"), jnp.int32)
    _out, out_lens = fn(docs, lens, pos, dlen, ilen, chars)
    got = np.asarray(out_lens)
    assert got[0] == -1 and got[1] == 2


def test_capacity_overflow_resyncs_then_converges():
    ol = _mk_oplog("grow")
    a = ol.get_or_create_agent_id("a")
    ol.add_insert(a, 0, "seed")
    sess = ff.FusedDocSession(ol, **FUSED_OPTS)
    r0 = sess.resyncs
    ol.add_insert(a, 0, "y" * 600)     # tail overflows cap=256
    sess.sync()
    sess.sync()
    assert sess.resyncs == r0 + 1
    assert sess.text() == ol.checkout_tip().snapshot()


# ---- bank-level: fused vs per-doc vs host --------------------------------

def test_sync_docs_three_engine_parity():
    """The same randomized bucket through fused, per-doc zone-session,
    and host banks — all three parity with the oplog authority."""
    rng = random.Random(23)
    docs = [f"p{i}" for i in range(4)]

    def run(engine, fused):
        ols = {d: _mk_oplog(d) for d in docs}
        # fresh rng per engine so all three see identical histories
        r = random.Random(77)
        for d in docs:
            _random_edits(ols[d], r, 3)
        bank = SessionBank(0, engine=engine, fused=fused,
                           fused_opts=FUSED_OPTS,
                           metrics=ServeMetrics(1, 4, 64))
        bank.sync_docs(_items(docs), ols.__getitem__)
        for d in docs:
            _random_edits(ols[d], r, 2)
        res = bank.sync_docs(_items(docs), ols.__getitem__)
        return {d: bank.text(d, ols[d]) for d in docs}, ols, res, bank

    fused_txt, fols, fres, fbank = run("device", True)
    perdoc_txt, pols, _pres, _ = run("device", False)
    host_txt, hols, _hres, _ = run("host", False)
    for d in docs:
        want = fols[d].checkout_tip().snapshot()
        assert fused_txt[d] == want
        assert perdoc_txt[d] == pols[d].checkout_tip().snapshot()
        assert host_txt[d] == hols[d].checkout_tip().snapshot()
        # identical seeds -> identical content across engines
        assert fused_txt[d] == perdoc_txt[d] == host_txt[d]
    # the second flush had 4 resident sessions with fresh tails: the
    # fused path must actually have fired, in ONE device call
    assert fres["fused_calls"] == 1 and fres["fused_docs"] == 4
    m = fbank.metrics.snapshot()
    assert m["fused"]["device_calls"] >= 1
    assert m["fused"]["occupancy"] > 1


def test_sync_docs_mixed_residency_falls_back_per_doc():
    """A non-fused session already resident in the bucket must not
    break the flush: it goes per-doc, the rest still parity."""
    from diamond_types_tpu.tpu.zone_session import DeviceZoneSession
    docs = ["m0", "m1", "m2"]
    ols = {d: _mk_oplog(d) for d in docs}
    rng = random.Random(5)
    for d in docs:
        _random_edits(ols[d], rng, 3)
    bank = SessionBank(0, engine="device", fused=True,
                       fused_opts=FUSED_OPTS,
                       metrics=ServeMetrics(1, 4, 64))
    # pre-plant a legacy per-doc session for m0
    bank.sessions["m0"] = DeviceZoneSession(ols["m0"])
    bank._resyncs_seen["m0"] = 0
    bank.sync_docs(_items(docs), ols.__getitem__)
    for d in docs:
        _random_edits(ols[d], rng, 2)
    res = bank.sync_docs(_items(docs), ols.__getitem__)
    assert res["fallback_docs"] >= 1     # m0 went per-doc
    for d in docs:
        assert bank.text(d, ols[d]) == \
            ols[d].checkout_tip().snapshot()


def test_poisoned_lens_propagates_to_host_fallback(monkeypatch):
    """A fused result whose length comes back poisoned/mismatched must
    evict the session and serve the doc from the host engine — the
    `lens == -1` contract propagating through sync_docs."""
    docs = ["x0", "x1"]
    ols = {d: _mk_oplog(d) for d in docs}
    rng = random.Random(9)
    for d in docs:
        _random_edits(ols[d], rng, 3)
    metrics = ServeMetrics(1, 4, 64)
    bank = SessionBank(0, engine="device", fused=True,
                       fused_opts=FUSED_OPTS, metrics=metrics)
    bank.sync_docs(_items(docs), ols.__getitem__)   # builds
    for d in docs:
        _random_edits(ols[d], rng, 2)

    real_plan = ff.FusedDocSession.plan_tail

    def bad_plan(self):
        plan = real_plan(self)
        if self.oplog.doc_id == "x0" and plan.n_ops:
            # a delete longer than max_ins reaching the kernel: the
            # device poisons this doc's length to -1
            plan.dlen[0] = self.max_ins + 1
        return plan

    monkeypatch.setattr(ff.FusedDocSession, "plan_tail", bad_plan)
    res = bank.sync_docs(_items(docs), ols.__getitem__)
    monkeypatch.undo()
    assert res["fused_calls"] == 1
    assert "x0" not in bank.sessions          # evicted
    snap = metrics.snapshot()
    assert snap["totals"]["host_fallbacks"] == 1
    # both docs still serve correct bytes (x0 from the host oracle)
    for d in docs:
        assert bank.text(d, ols[d]) == \
            ols[d].checkout_tip().snapshot()


# ---- scheduler-level: workers, concurrency, fencing ----------------------

def _two_shard_docs(sched, n=2):
    """Doc ids rendezvous-routed to shards 0 and 1, n per shard."""
    by_shard = {0: [], 1: []}
    i = 0
    while any(len(v) < n for v in by_shard.values()):
        d = f"w{i:03d}"
        s = sched.router.shard_of(d)
        if s in by_shard and len(by_shard[s]) < n:
            by_shard[s].append(d)
        i += 1
        assert i < 4096
    return by_shard


def test_two_shard_concurrent_flush_windows():
    """The worker pool + per-device locks must let two shards' flush
    windows OVERLAP: each shard's worker blocks on a shared barrier
    inside sync_docs, which only releases when both are inside their
    flush simultaneously. A process-global sync lock (the pre-fusion
    design) would deadlock the barrier."""
    ols = {}
    sched = MergeScheduler(2, resolve=lambda d: ols[d],
                           engine="device", fused=True,
                           fused_opts=FUSED_OPTS,
                           flush_docs=2, flush_deadline_s=10.0,
                           flush_workers=True)
    by_shard = _two_shard_docs(sched)
    rng = random.Random(3)
    for shard_docs in by_shard.values():
        for d in shard_docs:
            ols[d] = _mk_oplog(d)
            _random_edits(ols[d], rng, 2)

    barrier = threading.Barrier(2, timeout=10)
    overlapped = []
    orig = SessionBank.sync_docs

    def synced_sync_docs(self, items, resolve, **kw):
        try:
            barrier.wait()
            overlapped.append(self.shard_id)
        except threading.BrokenBarrierError:   # pragma: no cover
            pass
        return orig(self, items, resolve, **kw)

    SessionBank.sync_docs = synced_sync_docs
    try:
        for shard_docs in by_shard.values():
            for d in shard_docs:
                assert sched.submit(d, n_ops=1)["accepted"]
        sched.pump(force=True)
        sched.drain()
    finally:
        SessionBank.sync_docs = orig
        sched.stop_workers()
    assert sorted(overlapped) == [0, 1], overlapped
    assert not barrier.broken
    for d, ol in ols.items():
        assert sched.text(d) == ol.checkout_tip().snapshot()


def test_fencing_recheck_runs_inside_worker():
    """Work admitted under a lease epoch the host no longer holds must
    be dropped BY THE WORKER at flush time, not merged."""
    ols = {}
    sched = MergeScheduler(1, resolve=lambda d: ols[d],
                           engine="device", fused=True,
                           fused_opts=FUSED_OPTS,
                           flush_docs=8, flush_deadline_s=10.0,
                           flush_workers=True)
    epoch = {"n": 1}
    sched.epoch_of = lambda d: epoch["n"]
    d = "fenced-doc"
    ols[d] = _mk_oplog(d)
    a = ols[d].get_or_create_agent_id("a")
    ols[d].add_insert(a, 0, "hello")
    assert sched.submit(d, n_ops=1)["accepted"]
    epoch["n"] = 2        # the lease moved between admit and flush
    sched.pump(force=True)
    sched.drain()
    sched.stop_workers()
    m = sched.metrics_json()
    assert m["totals"]["fenced"] == 1
    assert m["totals"]["syncs"] == 0      # never merged
    assert d not in sched.banks[0].sessions


def test_scheduler_fused_end_to_end_counters():
    """Two pump rounds through one shard: round 1 builds, round 2 must
    fold the whole bucket into one fused device call, with the
    occupancy histogram and devprof attribution populated."""
    from diamond_types_tpu.obs.devprof import PROFILER
    ols = {}
    sched = MergeScheduler(1, resolve=lambda d: ols[d],
                           engine="device", fused=True,
                           fused_opts=FUSED_OPTS,
                           flush_docs=8, flush_deadline_s=10.0,
                           flush_workers=False)
    docs = [f"e{i}" for i in range(3)]
    rng = random.Random(1)
    PROFILER.reset()
    PROFILER.enabled = True
    try:
        for rnd in range(2):
            for d in docs:
                if rnd == 0:
                    ols[d] = _mk_oplog(d)
                _random_edits(ols[d], rng, 2)
                assert sched.submit(d, n_ops=1)["accepted"]
            sched.pump(force=True)
        m = sched.metrics_json()
        assert m["version"] == 13
        assert m["fused"]["device_calls"] >= 1
        assert m["fused"]["occupancy"] > 1
        assert m["fused"]["occupancy_hist"]
        dp = PROFILER.snapshot()
        assert dp["fused"]["device_calls"] == \
            m["fused"]["device_calls"]
        assert dp["fused"]["docs"] == m["fused"]["docs"]
        assert "fused" in dp["jit_cache"]
    finally:
        PROFILER.enabled = False
    for d in docs:
        assert sched.text(d) == ols[d].checkout_tip().snapshot()


# ---- warmup + jit cache --------------------------------------------------

def test_warmup_populates_fused_jit_cache():
    from diamond_types_tpu.obs.devprof import PROFILER
    PROFILER.reset()
    PROFILER.enabled = True
    try:
        # tiny dedicated shape class so this test owns its cache keys
        n = ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                                  shape_classes=(1,))
        assert n == 2        # batches {1, 2} x one op class
        snap1 = PROFILER.snapshot()["jit_cache"]["fused"]
        # a second warmup over the same shapes is all hits
        ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                              shape_classes=(1,))
        snap2 = PROFILER.snapshot()["jit_cache"]["fused"]
        assert snap2["hits"] >= snap1["hits"] + 2
        assert snap2["misses"] == snap1["misses"]
    finally:
        PROFILER.enabled = False


def test_bank_background_warmup_thread_joins():
    bank = SessionBank(0, engine="device", fused=True,
                       fused_opts={"cap": 64, "max_ins": 2},
                       warmup=True, flush_docs=2)
    bank.join_warmup()
    assert bank._warmup_thread is not None
    assert not bank._warmup_thread.is_alive()


# ---- prom rendering of the fused block -----------------------------------

def test_prom_renders_fused_block():
    from diamond_types_tpu.obs.prom import render_metrics
    m = ServeMetrics(1, 4, 64)
    m.record_fused(0, 3)
    m.record_fused(0, 3)
    text = render_metrics({"serve": m.snapshot()})
    assert "dt_serve_fused_occupancy 3.0" in text
    assert 'dt_serve_fused_flush_total{docs="3"} 2' in text
    assert "dt_serve_fused_calls_total 2" in text
    assert "dt_serve_fused_docs_total 6" in text
    # one TYPE line per family, no duplicates
    lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(lines) == len(set(lines))


# ---- CLI flags -----------------------------------------------------------

def test_cli_serve_bench_fused_flags_smoke(capsys):
    """--fused/--no-fused, --workers/--no-workers, --warmup, --parity,
    --steady-rounds all parse and the dry-run smoke passes parity."""
    from diamond_types_tpu.tools.cli import main
    rc = main(["serve-bench", "--dry-run", "--no-fused",
               "--no-workers", "--parity", "--steady-rounds", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "parity OK" in out
    assert "fused=off" in out
