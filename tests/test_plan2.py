"""Fork/join plan engine (listmerge2 re-expression) vs the M1 engine —
the reference's cross-engine differential strategy (reference:
src/listmerge2/test_conversion.rs validates MergePlans against listmerge)."""

import os

import pytest

from diamond_types_tpu.listmerge.dense import (DenseExecutor, apply_xf_stream,
                                               merge_via_plan2)
from diamond_types_tpu.listmerge.plan2 import (APPLY, BEGIN, FORK, MAX,
                                               compile_plan2, validate_plan2)
from tests.test_encode import build_random_oplog
from tests.test_linearize import _fuzz_oplog


def _checkout_text_plan2(ol, frontier=None):
    rows, final = merge_via_plan2(ol, [], frontier or ol.version,
                                  validate=True)
    return apply_xf_stream(ol, "", rows), final


# ---- plan structure ------------------------------------------------------

@pytest.mark.parametrize("seed", range(20))
def test_plan2_validates(seed):
    ol = build_random_oplog(seed, steps=45)
    plan = compile_plan2(ol.cg.graph, [], ol.version)
    validate_plan2(plan)
    assert plan.num_ops() == len(ol)


def test_plan2_linear_history_is_pure_ff():
    from diamond_types_tpu.text.oplog import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    v = []
    for i, ch in enumerate("hello"):
        v = [ol.add_insert_at(a, v, i, ch)]
    plan = compile_plan2(ol.cg.graph, [], ol.version)
    assert plan.entries == [] and plan.actions == []
    assert sum(b - a for (a, b) in plan.ff_spans) == 5


def test_plan2_fork_join_shape():
    """A 2-way concurrent edit produces a fork or two Begins plus a Max."""
    from diamond_types_tpu.text.oplog import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    base = [ol.add_insert_at(a, [], 0, "X")]
    va = [ol.add_insert_at(a, base, 1, "a")]
    vb = [ol.add_insert_at(b, base, 1, "b")]
    merge = ol.cg.graph.version_union(va, vb)
    plan = compile_plan2(ol.cg.graph, [], merge)
    validate_plan2(plan)
    kinds = [act[0] for act in plan.actions]
    assert kinds.count(APPLY) == len(plan.entries)
    assert FORK in kinds or kinds.count(BEGIN) >= 2
    assert MAX not in kinds or plan.indexes_used >= 2


# ---- differential parity vs M1 ------------------------------------------

@pytest.mark.parametrize("seed", range(25))
def test_plan2_checkout_matches_m1(seed):
    ol = build_random_oplog(seed, steps=45)
    expected = ol.checkout_tip().snapshot()
    got, final = _checkout_text_plan2(ol)
    assert got == expected
    assert final == ol.version


@pytest.mark.parametrize("seed", range(12))
def test_plan2_incremental_matches_m1(seed):
    ol = build_random_oplog(100 + seed, steps=35)
    mid = ol.cg.graph.find_dominators([len(ol) // 2])
    base = ol.checkout(mid)
    m1 = ol.checkout(mid)
    m1.merge(ol, ol.version)
    rows, final = merge_via_plan2(ol, mid, ol.version, validate=True)
    got = apply_xf_stream(ol, base.snapshot(), rows)
    assert got == m1.snapshot()
    assert final == m1.version


@pytest.mark.parametrize("seed", range(10))
def test_plan2_cross_sync_fuzz(seed):
    """The hard shape: origins that are themselves tie-broken concurrent
    inserts (mid-run oplog exchange between peers)."""
    ol = _fuzz_oplog(seed, steps=30, cross_sync=True)
    expected = ol.checkout_tip().snapshot()
    got, final = _checkout_text_plan2(ol)
    assert got == expected
    assert final == ol.version


@pytest.mark.parametrize("seed", range(8))
def test_plan2_random_from_merge_pairs(seed):
    """Arbitrary (from, merge) frontier pairs — the incremental-merge shape
    the device path also has to serve (reference: merge.rs:618
    TransformedOpsIter::new takes `from`)."""
    import random
    ol = _fuzz_oplog(200 + seed, steps=25, cross_sync=True)
    rng = random.Random(seed)
    for _ in range(4):
        lv_a = rng.randrange(len(ol))
        from_f = ol.cg.graph.find_dominators([lv_a])
        merge_f = ol.version if rng.random() < 0.5 else \
            ol.cg.graph.find_dominators(
                [rng.randrange(len(ol)), len(ol) - 1])
        base = ol.checkout(from_f)
        m1 = ol.checkout(from_f)
        m1.merge(ol, merge_f)
        rows, final = merge_via_plan2(ol, from_f, merge_f, validate=True)
        got = apply_xf_stream(ol, base.snapshot(), rows)
        assert got == m1.snapshot()
        assert final == m1.version


def test_plan2_is_static_schedule():
    ol = build_random_oplog(7, steps=40)
    plan = compile_plan2(ol.cg.graph, [], ol.version)
    r1 = [(lv, pos) for (lv, _o, pos) in
          DenseExecutor(plan, ol.cg.agent_assignment, ol.ops).run()]
    r2 = [(lv, pos) for (lv, _o, pos) in
          DenseExecutor(plan, ol.cg.agent_assignment, ol.ops).run()]
    assert r1 == r2


# ---- shipped corpora -----------------------------------------------------

def _reference_path(*parts):
    return os.path.join("/root/reference", *parts)


def test_plan2_friendsforever_corpus():
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(_reference_path("benchmark_data", "friendsforever.dt"),
              "rb") as f:
        ol = load_oplog(f.read())
    expected = ol.checkout_tip().snapshot()
    got, final = _checkout_text_plan2(ol)
    assert got == expected
    assert final == ol.version


def test_branch_merge_plan2_backend(monkeypatch):
    """DT_TPU_PLAN2=1 selects the fork/join engine behind the same
    Branch.merge seam the other engines use (the reference keeps
    listmerge2 behind the same boundary)."""
    for seed in (3, 11):
        ol = _fuzz_oplog(400 + seed, steps=25, cross_sync=True)
        # oracle via the default engines, with the switch unset
        monkeypatch.delenv("DT_TPU_PLAN2", raising=False)
        oracle = ol.checkout_tip()
        monkeypatch.setenv("DT_TPU_PLAN2", "1")
        b = ol.checkout([])          # trivial []->[] merge, also plan2
        b.merge(ol, ol.version)      # the real merge through plan2
        assert b.snapshot() == oracle.snapshot()
        assert b.version == oracle.version
