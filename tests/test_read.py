"""Follower reads (read/): staleness contract, cache, acceptance.

Covers the follower-read PR top to bottom:
  * FollowerIndex — advert/reconcile evidence, tightest-bound
    staleness, per-peer isolation, lag accounting;
  * CheckoutCache — LRU bound, per-doc invalidation, single-flight
    coalescing under a real thread flash-crowd;
  * ReadMetrics — fixed key surface (typos raise), snapshot shape,
    prom rendering of the dt_read_* families;
  * the two-server acceptance story: a follower serves within its
    staleness bound, refuses (or proxies) when a partition starves its
    evidence, and honors an X-DT-Min-Version token again after heal;
  * a tiny end-to-end run of the read-bench harness.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from diamond_types_tpu.read import (CheckoutCache, FollowerIndex,
                                    READ_KEYS, ReadMetrics)
from diamond_types_tpu.read.cache import frontier_key
from diamond_types_tpu.read.follower import frontier_known
from diamond_types_tpu.replicate import FaultInjector, attach_replication

pytestmark = pytest.mark.read


# ---- FollowerIndex -------------------------------------------------------

def test_index_no_evidence_is_unbounded():
    idx = FollowerIndex()
    assert idx.staleness("d", "owner", lambda fr: True) is None
    assert idx.lag("d", "owner", lambda fr: True) is None


def test_index_advert_bounds_staleness_only_when_dominated():
    idx = FollowerIndex()
    idx.note_advert("d", "owner", [["a", 3]], as_of=100.0)
    # local oplog dominates the advert: bounded by now - as_of
    st = idx.staleness("d", "owner", lambda fr: True, now=100.5)
    assert st == pytest.approx(0.5)
    # local oplog does NOT dominate: the advert proves nothing
    assert idx.staleness("d", "owner", lambda fr: False,
                         now=100.5) is None


def test_index_reconcile_floor_needs_no_dominance():
    idx = FollowerIndex()
    idx.note_reconciled("d", "owner", as_of=200.0)
    st = idx.staleness("d", "owner", lambda fr: False, now=201.0)
    assert st == pytest.approx(1.0)
    # floors only ratchet forward
    idx.note_reconciled("d", "owner", as_of=150.0)
    assert idx.staleness("d", "owner", lambda fr: False,
                         now=201.0) == pytest.approx(1.0)


def test_index_takes_tightest_bound_and_clamps():
    idx = FollowerIndex()
    idx.note_reconciled("d", "owner", as_of=100.0)
    idx.note_advert("d", "owner", [["a", 1]], as_of=104.0)
    st = idx.staleness("d", "owner", lambda fr: True, now=105.0)
    assert st == pytest.approx(1.0)        # advert, not the reconcile
    # evidence "from the future" (sub-RTT slop) clamps to zero
    assert idx.staleness("d", "owner", lambda fr: True,
                         now=103.0) == 0.0


def test_index_adverts_are_per_peer():
    """A stale lease holder's late advert must not clobber the real
    owner's — evidence is keyed by peer and filtered at query time."""
    idx = FollowerIndex()
    idx.note_advert("d", "old-owner", [["a", 9]], as_of=300.0)
    idx.note_advert("d", "owner", [["a", 2]], as_of=310.0)
    fr, as_of = idx.advert_of("d", "owner")
    assert fr == [["a", 2]] and as_of == 310.0
    assert idx.staleness("d", "owner", lambda fr: True,
                         now=311.0) == pytest.approx(1.0)
    # an older advert from the same peer never replaces a newer one
    idx.note_advert("d", "owner", [["a", 1]], as_of=305.0)
    assert idx.advert_of("d", "owner")[1] == 310.0


def test_index_lag_counts_missing_heads():
    idx = FollowerIndex()
    idx.note_advert("d", "owner", [["a", 5], ["b", 2]], as_of=1.0)
    have = {("a", 5)}
    lag = idx.lag("d", "owner",
                  lambda fr: tuple((h[0], h[1]) for h in fr)[0] in have)
    assert lag == 1
    have.add(("b", 2))
    assert idx.lag("d", "owner",
                   lambda fr: (fr[0][0], fr[0][1]) in have) == 0
    idx.forget("d")
    assert idx.lag("d", "owner", lambda fr: True) is None


def test_frontier_known_against_real_oplog():
    from diamond_types_tpu.text.oplog import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    ol.add_insert(a, 0, "hey")
    remote = ol.cg.local_to_remote_frontier(ol.version)
    assert frontier_known(ol, remote)
    agent, seq = remote[0][0], int(remote[0][1])
    assert not frontier_known(ol, [[agent, seq + 1]])
    assert not frontier_known(ol, [["nobody", 0]])


# ---- CheckoutCache -------------------------------------------------------

def test_cache_hit_miss_and_lru_eviction():
    m = ReadMetrics()
    c = CheckoutCache(capacity=2, metrics=m)
    k = frontier_key([["a", 1]])
    assert c.get("d0", k, lambda: "v0") == ("v0", "miss")
    assert c.get("d0", k, lambda: "BOOM") == ("v0", "hit")
    c.get("d1", k, lambda: "v1")
    c.get("d0", k, lambda: "BOOM")          # refresh d0's recency
    c.get("d2", k, lambda: "v2")            # evicts d1 (LRU)
    assert c.get("d1", k, lambda: "v1b") == ("v1b", "miss")
    snap = m.snapshot()["counters"]
    assert snap["cache_hits"] == 2
    assert snap["cache_misses"] == 4
    assert snap["cache_evictions"] >= 1


def test_cache_invalidate_drops_every_frontier_of_doc():
    m = ReadMetrics()
    c = CheckoutCache(capacity=8, metrics=m)
    for seq in (1, 2, 3):
        c.get("d0", frontier_key([["a", seq]]), lambda: f"v{seq}")
    c.get("other", frontier_key([["a", 1]]), lambda: "keep")
    assert c.invalidate("d0") == 3
    assert len(c) == 1
    assert c.invalidate("d0") == 0
    assert c.get("other", frontier_key([["a", 1]]),
                 lambda: "BOOM") == ("keep", "hit")
    assert m.snapshot()["counters"]["invalidated_entries"] == 3


def test_cache_single_flight_coalesces_flash_crowd():
    m = ReadMetrics()
    c = CheckoutCache(capacity=8, metrics=m)
    k = frontier_key([["a", 1]])
    entered = threading.Event()
    release = threading.Event()
    calls = []

    def materialize():
        calls.append(1)
        entered.set()
        release.wait(5)
        return "value"

    results = []

    def leader():
        results.append(c.get("d", k, materialize))

    def waiter():
        results.append(c.get("d", k, lambda: "WRONG"))

    lt = threading.Thread(target=leader)
    lt.start()
    assert entered.wait(5)
    ws = [threading.Thread(target=waiter) for _ in range(3)]
    for w in ws:
        w.start()
    time.sleep(0.05)        # waiters parked on the flight event
    release.set()
    lt.join(5)
    for w in ws:
        w.join(5)
    assert len(calls) == 1
    assert {r[0] for r in results} == {"value"}
    outcomes = sorted(r[1] for r in results)
    assert outcomes == ["coalesced", "coalesced", "coalesced", "miss"]
    assert m.snapshot()["counters"]["cache_coalesced"] == 3


def test_cache_leader_failure_releases_waiters():
    c = CheckoutCache(capacity=8, flight_timeout_s=2.0)
    k = frontier_key([["a", 1]])
    entered = threading.Event()
    outcome = []

    def bad():
        entered.set()
        time.sleep(0.1)
        raise RuntimeError("materialize failed")

    def leader():
        with pytest.raises(RuntimeError):
            c.get("d", k, bad)

    lt = threading.Thread(target=leader)
    lt.start()
    assert entered.wait(5)
    # waiter sees the leader's failure and materializes for itself
    outcome.append(c.get("d", k, lambda: "mine"))
    lt.join(5)
    assert outcome[0] == ("mine", "timeout")
    assert len(c) == 0      # failed flight cached nothing


# ---- ReadMetrics ---------------------------------------------------------

def test_metrics_fixed_keys_and_snapshot_shape():
    m = ReadMetrics()
    with pytest.raises(KeyError):
        m.bump("no_such_counter")
    m.bump("reads", 4)
    m.bump("local", 3)
    m.bump("proxied_staleness")
    m.observe_staleness(0.25)
    snap = m.snapshot()
    assert snap["version"] == 2
    assert set(snap["counters"]) == set(READ_KEYS)
    assert snap["proxied"] == 1
    assert snap["local_ratio"] == pytest.approx(0.75)
    assert snap["staleness"]["count"] == 1
    assert ReadMetrics().snapshot()["local_ratio"] is None


def test_prom_renders_read_families():
    from diamond_types_tpu.obs.prom import render_metrics
    m = ReadMetrics()
    m.bump("reads", 2)
    m.bump("local", 2)
    m.observe_staleness(0.1)
    m.observe_wait(0.02)
    text = render_metrics({"read": m.snapshot()})
    assert "dt_read_reads_total 2" in text
    assert "dt_read_local_total 2" in text
    assert "dt_read_local_ratio 1" in text
    assert "dt_read_staleness_seconds_count 1" in text
    assert "dt_read_wait_latency_seconds_count 1" in text
    # inside a ServeMetrics v8 snapshot the same families render once
    from diamond_types_tpu.serve.metrics import ServeMetrics
    sm = ServeMetrics(n_shards=1, flush_docs=8, max_pending=64)
    sm.read = m
    text2 = render_metrics({"serve": sm.snapshot()})
    assert text2.count("dt_read_reads_total 2") == 1


# ---- two-server acceptance -----------------------------------------------

def _mesh2(faults=None, read_opts=None):
    from diamond_types_tpu.read import attach_follower_reads
    from diamond_types_tpu.tools.server import serve
    httpds, addrs, nodes = [], [], []
    for _ in range(2):
        httpd = serve(port=0, serve_shards=1)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            faults=faults, lease_ttl_s=30.0, timeout_s=0.5,
            backoff_base_s=0.01, backoff_cap_s=0.05))
        attach_follower_reads(httpd.store, **(read_opts or {}))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def _teardown(httpds):
    for h in httpds:
        h.shutdown()
        h.server_close()


def _step(nodes, rounds=1):
    for _ in range(rounds):
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            n.antientropy.run_round()


def _edit(addr, doc, agent, version, text):
    req = urllib.request.Request(
        f"http://{addr}/doc/{doc}/edit",
        data=json.dumps({"agent": agent, "version": version,
                         "ops": [{"kind": "ins", "pos": 0,
                                  "text": text}]}).encode("utf8"))
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())["version"]


def _read(addr, doc, max_staleness=None, token=None):
    """Returns (status, headers, body-dict-or-None)."""
    url = f"http://{addr}/doc/{doc}/state"
    if max_staleness is not None:
        url += f"?max_staleness={max_staleness}"
    headers = {}
    if token is not None:
        headers["X-DT-Min-Version"] = json.dumps(token)
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), \
            (json.loads(body) if body else None)


def _settle_owner(nodes, doc):
    """Step until exactly one node holds the ACTIVE lease; returns
    (owner, follower)."""
    for _ in range(200):
        _step(nodes)
        holders = [n for n in nodes if n.leases.active_epoch(doc) > 0]
        if len(holders) == 1:
            owner = holders[0]
            follower = next(n for n in nodes if n is not owner)
            if follower.route_mutation(doc) == owner.self_id:
                return owner, follower
        time.sleep(0.02)
    raise AssertionError("lease never settled")


def _dominated(headers, token):
    heads = {a: int(s)
             for a, s in json.loads(headers["X-DT-Frontier"])}
    return all(heads.get(a, -1) >= int(s) for a, s in token)


def test_follower_partition_refuses_then_honors_token_after_heal():
    """The acceptance story: a partitioned follower whose evidence has
    aged past the bound refuses (proxy unreachable) instead of serving
    out of contract, and serves a write's min-version token locally
    again after heal + anti-entropy."""
    faults = FaultInjector(seed=3)
    httpds, nodes, addrs = _mesh2(
        faults=faults, read_opts={"max_wait_s": 0.05})
    try:
        doc = "accept0"
        _edit(addrs[0], doc, "w", [], "hello ")
        owner, follower = _settle_owner(nodes, doc)
        _step(nodes, rounds=2)      # fresh adverts + reconcile floors

        # 1) healthy mesh: the follower serves locally, in contract,
        #    and says how stale it might be
        st, hdr, body = _read(follower.self_id, doc, max_staleness=10.0)
        assert st == 200
        assert hdr["X-DT-Read-Source"] == "local"
        assert float(hdr["X-DT-Staleness"]) <= 10.0
        assert hdr["Cache-Control"] == "no-store"
        assert "hello" in body["text"]

        # 2) an unsatisfiable bound on a healthy mesh falls back to
        #    the owner proxy instead of refusing
        st, hdr, _ = _read(follower.self_id, doc, max_staleness=0.0)
        assert st == 200
        assert hdr["X-DT-Read-Source"] == "proxied"

        # 3) partition: evidence ages past the bound and the proxy
        #    path is dead -> the follower must refuse, not serve
        faults.partition(owner.self_id, follower.self_id)
        time.sleep(0.25)
        st, _, body = _read(follower.self_id, doc, max_staleness=0.01)
        assert st == 503
        assert body["error"] == "read contract unsatisfiable"

        # 4) a write lands at the owner during the partition (client
        #    traffic is not fault-injected, only the peer mesh is);
        #    its token is unsatisfiable at the follower
        token = _edit(owner.self_id, doc, "w", None, "more ")
        st, _, _ = _read(follower.self_id, doc, max_staleness=10.0,
                         token=token)
        assert st == 503
        fm = follower.store.reads.metrics.snapshot()["counters"]
        assert fm["refused"] >= 2
        assert fm["catchup_timeouts"] >= 1

        # 5) heal: circuits close, anti-entropy reconciles, and the
        #    same token is served locally with a dominating frontier
        faults.heal(owner.self_id, follower.self_id)
        for _ in range(50):
            _step(nodes)
            st, hdr, body = _read(follower.self_id, doc,
                                  max_staleness=10.0, token=token)
            if st == 200 and hdr["X-DT-Read-Source"] == "local":
                break
            time.sleep(0.02)
        assert st == 200
        assert hdr["X-DT-Read-Source"] == "local"
        assert _dominated(hdr, token)
        assert "more" in body["text"]
        fm = follower.store.reads.metrics.snapshot()["counters"]
        assert fm["local"] >= 2
        assert fm["adverts"] >= 1
    finally:
        _teardown(httpds)


def test_owner_side_of_proxy_never_loops():
    """X-DT-Proxied marks the owner side of a hop: it serves locally
    (still honoring the token) and refuses rather than re-proxying."""
    httpds, nodes, addrs = _mesh2(read_opts={"max_wait_s": 0.05})
    try:
        doc = "loop0"
        _edit(addrs[0], doc, "w", [], "x")
        owner, follower = _settle_owner(nodes, doc)
        # a forced-local read on the FOLLOWER with an unsatisfiable
        # token must refuse (503), never hop again
        bogus = [["w", 10_000]]
        req = urllib.request.Request(
            f"http://{follower.self_id}/doc/{doc}/state",
            headers={"X-DT-Proxied": "1",
                     "X-DT-Min-Version": json.dumps(bogus)})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 503
        ei.value.read()
        snap = follower.store.reads.metrics.snapshot()["counters"]
        assert snap["proxied_forced"] >= 1
        assert snap["refused"] >= 1
    finally:
        _teardown(httpds)


def test_read_bench_smoke_end_to_end():
    """Tiny end-to-end run of the A/B harness: settles, verifies every
    response, reports both phases and per-node read metrics."""
    from diamond_types_tpu.read.bench import run_read_bench
    report = run_read_bench(docs=2, readers=2, reads_per_reader=10,
                            seed=11, doc_bytes=2048, min_speedup=None)
    assert report["settled"]
    assert report["violations"] == 0
    assert report["errors"] == 0
    assert report["control"]["reads"] == 20
    assert report["follower"]["reads"] == 20
    assert report["follower"]["local"] == 20
    assert report["control"]["proxied"] == 20
    for snap in report["read_metrics"].values():
        assert snap["version"] == 2
