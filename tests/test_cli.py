"""CLI smoke tests (reference: crates/dt-cli)."""

import json
import subprocess
import sys

from diamond_types_tpu.tools import cli


def run(args):
    return cli.main(args)


def test_cli_roundtrip(tmp_path, capsys):
    f = str(tmp_path / "doc.dt")
    assert run(["create", f, "--content", "hello world", "--agent", "seph"]) == 0
    assert run(["cat", f]) == 0
    assert capsys.readouterr().out == "hello world"

    assert run(["set", f, "--content", "hello brave world", "--agent", "seph"]) == 0
    assert run(["cat", f]) == 0
    assert capsys.readouterr().out == "hello brave world"

    assert run(["version", f]) == 0
    ver = json.loads(capsys.readouterr().out)
    assert ver[0][0] == "seph"

    assert run(["log", f, "--history"]) == 0
    rows = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert rows[0]["agent"] == "seph"

    assert run(["repack", f]) == 0
    capsys.readouterr()
    assert run(["dot", f]) == 0
    assert "digraph" in capsys.readouterr().out
    assert run(["export", f]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["endContent"] == "hello brave world"


def test_git_import(tmp_path, capsys):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", "-C", str(repo)] + list(args), check=True,
                       capture_output=True,
                       env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@x",
                            "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@x",
                            "PATH": "/usr/bin:/bin:/usr/local/bin",
                            "HOME": str(tmp_path)})

    git("init", "-b", "main")
    (repo / "a.txt").write_text("one\n")
    git("add", "a.txt")
    git("commit", "-m", "c1")
    (repo / "a.txt").write_text("one\ntwo\n")
    git("commit", "-am", "c2")
    # branch + merge to build a non-linear DAG
    git("checkout", "-b", "side", "HEAD~1")
    (repo / "a.txt").write_text("zero\none\n")
    git("commit", "-am", "c3")
    git("checkout", "main")
    git("merge", "side", "-m", "merge")

    out = str(tmp_path / "a.dt")
    assert run(["git-import", "a.txt", "--repo", str(repo), "--out", out]) == 0
    capsys.readouterr()
    assert run(["cat", out]) == 0
    text = capsys.readouterr().out
    assert "one" in text and "two" in text and "zero" in text
