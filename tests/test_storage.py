"""Crash-safety tests for WAL + page store + DocFile (reference: src/wal.rs,
src/storage/, src/causalgraph/storage.rs — SURVEY.md §5 failure handling)."""

import os
import random

import pytest

from diamond_types_tpu.storage.store import DocFile, PageStore, Wal
from tests.test_encode import build_random_oplog, semantic_eq
from tests.test_fuzz import random_edit


def test_wal_roundtrip_and_torn_tail(tmp_path):
    p = str(tmp_path / "log.wal")
    w = Wal(p)
    w.append(b"alpha")
    w.append(b"beta" * 100)
    w.close()

    # Simulate a torn write: append garbage / a partial frame.
    with open(p, "ab") as f:
        f.write(b"\x50\x00\x00\x00\xde\xad\xbe\xefpartial")

    w2 = Wal(p)
    assert list(w2.records()) == [b"alpha", b"beta" * 100]
    w2.append(b"gamma")
    assert list(w2.records()) == [b"alpha", b"beta" * 100, b"gamma"]
    w2.close()


def test_wal_corrupt_middle_stops_replay(tmp_path):
    p = str(tmp_path / "log.wal")
    w = Wal(p)
    w.append(b"one")
    w.append(b"two")
    w.close()
    data = bytearray(open(p, "rb").read())
    data[14] ^= 0xFF  # corrupt first record's payload
    open(p, "wb").write(bytes(data))
    w2 = Wal(p)
    assert list(w2.records()) == []  # replay stops at first bad record


def test_pagestore_survives_torn_header(tmp_path):
    p = str(tmp_path / "doc.store")
    ps = PageStore(p)
    ps.write(b"generation one")
    ps.write(b"generation two, longer " * 10)
    ps.close()

    # Corrupt the most recent header slot (gen=2 -> slot 0).
    data = bytearray(open(p, "rb").read())
    data[10] ^= 0xFF
    open(p, "wb").write(bytes(data))

    ps2 = PageStore(p)
    # Falls back to the older generation whose data prefix is still intact.
    assert ps2.read() == b"generation one"
    ps2.close()


def test_docfile_persist_reopen_compact(tmp_path):
    path = str(tmp_path / "doc.dtstore")
    ol = build_random_oplog(5, steps=30)

    d = DocFile(path)
    d.append_from(ol)
    d.close()

    d2 = DocFile(path)
    assert semantic_eq(d2.oplog, ol)

    # More edits, incremental append, WAL grows.
    rng = random.Random(1)
    v, c = ol.version, ol.checkout_tip().snapshot()
    for _ in range(10):
        v, c = random_edit(rng, ol, 0, v, c)
    d2.append_from(ol)
    assert semantic_eq(d2.oplog, ol)
    assert os.path.getsize(path + ".wal") > 8

    d2.compact()
    assert os.path.getsize(path + ".wal") == 8  # just the magic
    d2.close()

    d3 = DocFile(path)
    assert semantic_eq(d3.oplog, ol)
    d3.close()


def test_docfile_wal_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "doc.dtstore")
    ol = build_random_oplog(9, steps=20)
    d = DocFile(path)
    d.append_from(ol)
    d.close()

    with open(path + ".wal", "ab") as f:
        f.write(os.urandom(37))  # crash mid-append

    d2 = DocFile(path)
    assert semantic_eq(d2.oplog, ol)
    d2.close()
