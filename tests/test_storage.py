"""Crash-safety tests for WAL + page store + DocFile (reference: src/wal.rs,
src/storage/, src/causalgraph/storage.rs — SURVEY.md §5 failure handling)."""

import os
import random

import pytest

from diamond_types_tpu.storage.store import DocFile, PageStore, Wal
from tests.test_encode import build_random_oplog, semantic_eq
from tests.test_fuzz import random_edit


def test_wal_roundtrip_and_torn_tail(tmp_path):
    p = str(tmp_path / "log.wal")
    w = Wal(p)
    w.append(b"alpha")
    w.append(b"beta" * 100)
    w.close()

    # Simulate a torn write: append garbage / a partial frame.
    with open(p, "ab") as f:
        f.write(b"\x50\x00\x00\x00\xde\xad\xbe\xefpartial")

    w2 = Wal(p)
    assert list(w2.records()) == [b"alpha", b"beta" * 100]
    w2.append(b"gamma")
    assert list(w2.records()) == [b"alpha", b"beta" * 100, b"gamma"]
    w2.close()


def test_wal_corrupt_middle_stops_replay(tmp_path):
    p = str(tmp_path / "log.wal")
    w = Wal(p)
    w.append(b"one")
    w.append(b"two")
    w.close()
    data = bytearray(open(p, "rb").read())
    data[14] ^= 0xFF  # corrupt first record's payload
    open(p, "wb").write(bytes(data))
    w2 = Wal(p)
    assert list(w2.records()) == []  # replay stops at first bad record


def test_pagestore_survives_torn_header(tmp_path):
    p = str(tmp_path / "doc.store")
    ps = PageStore(p)
    ps.write(b"generation one")
    ps.write(b"generation two, longer " * 10)
    ps.close()

    # Corrupt the most recent header slot (gen=2 -> slot 0).
    data = bytearray(open(p, "rb").read())
    data[10] ^= 0xFF
    open(p, "wb").write(bytes(data))

    ps2 = PageStore(p)
    # Falls back to the older generation whose data prefix is still intact.
    assert ps2.read() == b"generation one"
    ps2.close()


def test_docfile_persist_reopen_compact(tmp_path):
    path = str(tmp_path / "doc.dtstore")
    ol = build_random_oplog(5, steps=30)

    d = DocFile(path)
    d.append_from(ol)
    d.close()

    d2 = DocFile(path)
    assert semantic_eq(d2.oplog, ol)

    # More edits, incremental append, WAL grows.
    rng = random.Random(1)
    v, c = ol.version, ol.checkout_tip().snapshot()
    for _ in range(10):
        v, c = random_edit(rng, ol, 0, v, c)
    d2.append_from(ol)
    assert semantic_eq(d2.oplog, ol)
    assert os.path.getsize(path + ".wal") > 8

    d2.compact()
    assert os.path.getsize(path + ".wal") == 8  # just the magic
    d2.close()

    d3 = DocFile(path)
    assert semantic_eq(d3.oplog, ol)
    d3.close()


def test_docfile_wal_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "doc.dtstore")
    ol = build_random_oplog(9, steps=20)
    d = DocFile(path)
    d.append_from(ol)
    d.close()

    with open(path + ".wal", "ab") as f:
        f.write(os.urandom(37))  # crash mid-append

    d2 = DocFile(path)
    assert semantic_eq(d2.oplog, ol)
    d2.close()


# ---- page-granular engine (reference: src/storage/mod.rs:103-505 +
# causalgraph/storage.rs incremental format) ----

def _big_doc(n_chars=100_000):
    from diamond_types_tpu import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("author")
    ol.add_insert_at(a, [], 0, "x" * n_chars)
    return ol, a


def test_paged_roundtrip(tmp_path):
    from diamond_types_tpu.storage.pages import PagedStore
    p = str(tmp_path / "s.pages")
    s = PagedStore(p)
    recs = [b"alpha", b"b" * 10_000, b"", b"tail-rec"]
    for r in recs:
        s.append(1, r)
    s.append(0, b"other-stream")
    s.close()
    s2 = PagedStore(p)
    assert list(s2.records(1)) == recs
    assert list(s2.records(0)) == [b"other-stream"]
    s2.append(1, b"after-reopen")
    s2.close()
    s3 = PagedStore(p)
    assert list(s3.records(1)) == recs + [b"after-reopen"]
    s3.close()


def test_paged_write_amplification(tmp_path):
    """A 1-char edit on a ~100KB doc persists O(1) pages, not O(doc)
    (the property the whole-snapshot blit store lacked — VERDICT r2
    missing #3)."""
    from diamond_types_tpu.storage.pages import PAGE_SIZE, PagedDocFile
    ol, a = _big_doc()
    path = str(tmp_path / "doc.pages")
    f = PagedDocFile(path)
    f.append_from(ol)        # baseline-sized write (the initial import)
    before = f.store.bytes_written
    v = list(ol.version)
    ol.add_insert_at(a, v, 5, "!")
    f.append_from(ol)        # ONE char of new history
    delta = f.store.bytes_written - before
    assert delta <= 3 * PAGE_SIZE, f"1-char edit wrote {delta} bytes"
    f.close()
    f2 = PagedDocFile(path)
    assert f2.oplog.checkout_tip().snapshot() == \
        ol.checkout_tip().snapshot()
    f2.close()


def test_paged_compact(tmp_path):
    import os
    from diamond_types_tpu.storage.pages import PagedDocFile
    ol, a = _big_doc(5_000)
    path = str(tmp_path / "doc.pages")
    f = PagedDocFile(path)
    f.append_from(ol)
    for i in range(30):
        ol.add_insert_at(a, list(ol.version), 0, f"edit{i} ")
        f.append_from(ol)
    size_before = os.path.getsize(path)
    f.compact()
    assert os.path.getsize(path) < size_before
    f.append_from(ol)   # still writable after compact
    f.close()
    f2 = PagedDocFile(path)
    assert f2.oplog.checkout_tip().snapshot() == \
        ol.checkout_tip().snapshot()
    f2.close()


def test_paged_crash_fuzz(tmp_path):
    """Corrupt/truncate the file at random byte boundaries after each
    append; reopening must always recover a consistent PREFIX of the
    record sequence (crash-safety invariant of the blit protocol)."""
    import os
    import random
    from diamond_types_tpu.storage.pages import PagedStore
    rng = random.Random(2024)
    for trial in range(15):
        p = str(tmp_path / f"c{trial}.pages")
        s = PagedStore(p)
        recs = []
        for i in range(rng.randint(2, 10)):
            r = bytes([rng.randrange(256)]) * rng.randint(1, 9000)
            s.append(1, r)
            recs.append(r)
        s.close()
        data = open(p, "rb").read()
        if rng.random() < 0.5:
            cut = rng.randrange(len(data))
            torn = data[:cut]
        else:
            pos = rng.randrange(max(1, len(data) - 64))
            torn = data[:pos] + bytes(
                rng.randrange(256) for _ in range(32)) + data[pos + 32:]
        open(p, "wb").write(torn)
        s2 = PagedStore(p)
        got = list(s2.records(1))
        assert got == recs[:len(got)], f"trial {trial}: not a prefix"
        # the store must remain APPENDABLE after recovery
        s2.append(1, b"post-crash")
        s2.close()
        s3 = PagedStore(p)
        got2 = list(s3.records(1))
        assert got2[-1] == b"post-crash"
        assert got2[:-1] == recs[:len(got2) - 1]
        s3.close()
        # SECOND crash cycle: recovery itself must leave a state that
        # survives another torn write (regression: a finalized page whose
        # newest image lived on the blit slot must be re-sealed at a main
        # slot during recovery, or the next blit reuse orphans it)
        data = open(p, "rb").read()
        cut = rng.randrange(max(1, len(data) - 2048), len(data))
        open(p, "wb").write(data[:cut])
        s4 = PagedStore(p)
        got3 = list(s4.records(1))
        expect_all = recs[:len(got2) - 1] + [b"post-crash"]
        assert got3 == expect_all[:len(got3)], \
            f"trial {trial}: second crash broke the prefix invariant"
        s4.close()


def _newest_image_slot(path, stream, idx):
    """Slot holding the newest on-disk image of (stream, idx)."""
    from diamond_types_tpu.storage.pages import _HDR, PAGE_SIZE
    from diamond_types_tpu.encoding.crc32c import crc32c
    data = open(path, "rb").read()
    hit, hit_key = None, None
    for slot in range(len(data) // PAGE_SIZE):
        raw = data[slot * PAGE_SIZE:(slot + 1) * PAGE_SIZE]
        crc, s, _b, used, i, gen, seq = _HDR.unpack(raw[:_HDR.size])
        if crc32c(raw[4:]) != crc:
            continue
        if s == stream and i == idx and (hit_key is None
                                         or (gen, seq) > hit_key):
            hit, hit_key = slot, (gen, seq)
    return hit


def test_paged_rollback_suffix_not_respliced(tmp_path):
    """ADVICE r3 (high): a crash tearing a record that SPANS pages leaves
    valid same-gen spill pages beyond the rolled-back tail; after a clean
    intervening append+close, the next recovery's chain walk used to
    splice those stale bytes back in as phantom records."""
    import struct
    from diamond_types_tpu.storage.pages import PAGE_SIZE, PagedStore
    p = str(tmp_path / "x.pages")
    s = PagedStore(p)
    rec1 = b"A" * 100
    # rec2's body is a stream of zero-length record frames: if its sealed
    # spill pages are ever spliced back, they parse as hundreds of empty
    # phantom records (the worst-case misparse from the advice repro)
    rec2 = struct.pack("<I", 0) * 2300   # 9200 bytes -> spans 3 pages
    s.append(1, rec1)
    s.append(1, rec2)
    s.close()
    # crash = the final tail write (idx 2) torn: zero that page image
    slot = _newest_image_slot(p, 1, 2)
    assert slot is not None
    data = bytearray(open(p, "rb").read())
    data[slot * PAGE_SIZE:(slot + 1) * PAGE_SIZE] = b"\0" * PAGE_SIZE
    open(p, "wb").write(bytes(data))

    s2 = PagedStore(p)   # rolls rec2 back (its tail bytes are gone)
    assert list(s2.records(1)) == [rec1]
    s2.append(1, b"fresh")
    s2.close()           # CLEAN close

    s3 = PagedStore(p)
    assert list(s3.records(1)) == [rec1, b"fresh"], \
        "stale spill pages of the rolled-back record were re-spliced"
    s3.append(1, b"more")
    s3.close()
    s4 = PagedStore(p)
    assert list(s4.records(1)) == [rec1, b"fresh", b"more"]
    s4.close()


def test_paged_first_post_recovery_write_torn(tmp_path):
    """The first tail write after recovery must target the slot NOT
    holding the newest tail image: if that write tears, previously
    committed records must still be readable (blit alternation parity
    must be re-derived at recovery, not inherited from seal_seq)."""
    from diamond_types_tpu.storage.pages import PAGE_SIZE, PagedStore

    # Drive both parities: vary the number of small appends pre-crash.
    for n_pre in (1, 2, 3, 4, 5):
        p = str(tmp_path / f"p{n_pre}.pages")
        s = PagedStore(p)
        recs = [bytes([65 + i]) * (10 + i) for i in range(n_pre)]
        for r in recs:
            s.append(1, r)
        s.close()
        # crash 1: truncate mid-final-page write (tear whatever was last)
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) - PAGE_SIZE // 2])
        s2 = PagedStore(p)
        got = list(s2.records(1))
        assert got == recs[:len(got)]
        committed = list(got)
        s2.append(1, b"after")
        s2.close()
        # crash 2: tear ONLY the newest tail image (the post-recovery
        # write); everything committed before it must survive
        slot = _newest_image_slot(p, 1, 0)
        data = bytearray(open(p, "rb").read())
        data[slot * PAGE_SIZE:(slot + 1) * PAGE_SIZE] = b"\0" * PAGE_SIZE
        open(p, "wb").write(bytes(data))
        s3 = PagedStore(p)
        got3 = list(s3.records(1))
        assert got3[:len(committed)] == committed, (
            f"n_pre={n_pre}: records committed before the torn "
            f"post-recovery write were lost: {got3} vs {committed}")
        s3.close()
