"""Cross-host replication tests: peer mesh, leases, anti-entropy,
fault injection (diamond_types_tpu/replicate/). Tier-1 safe: every
server is in-process on an ephemeral localhost port, no TPU, no
background control-plane threads (tests step probes/rounds inline for
determinism)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from diamond_types_tpu.replicate import (Backoff, CircuitOpen,
                                         FaultDrop, FaultInjector,
                                         PeerTable, ReplicaJournal,
                                         attach_replication,
                                         call_with_retries, owner_of)
from diamond_types_tpu.replicate.metrics import ReplicationMetrics
from diamond_types_tpu.replicate.ownership import (ACTIVE, GRANTED,
                                                   RELEASED,
                                                   LeaseManager)

pytestmark = pytest.mark.replicate


# ---- helpers -------------------------------------------------------------

def _mesh(n, tmp_path=None, serve_shards=2, faults=None,
          lease_ttl_s=5.0, **opts):
    """N wired in-process servers. Returns (httpds, nodes, addrs).
    Breaker backoff is tightened so circuits opened by injected faults
    half-open within one paced test round instead of seconds."""
    from diamond_types_tpu.tools.server import serve
    opts.setdefault("backoff_base_s", 0.01)
    opts.setdefault("backoff_cap_s", 0.05)
    httpds, addrs = [], []
    for i in range(n):
        data_dir = str(tmp_path / f"s{i}") if tmp_path else None
        httpd = serve(port=0, data_dir=data_dir,
                      serve_shards=serve_shards)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            faults=faults, lease_ttl_s=lease_ttl_s, **opts))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def _teardown(httpds):
    for h in httpds:
        h.shutdown()
        h.server_close()


def _step(nodes, rounds=1):
    for _ in range(rounds):
        for n in nodes:
            n.table.probe_once()
            n.maintain()
        for n in nodes:
            n.antientropy.run_round()


def _text(addr, doc):
    with urllib.request.urlopen(f"http://{addr}/doc/{doc}",
                                timeout=5) as r:
        return r.read().decode("utf8")


def _metrics(addr):
    with urllib.request.urlopen(f"http://{addr}/metrics",
                                timeout=5) as r:
        return json.loads(r.read())


# ---- unit: backoff / retries / faults ------------------------------------

def test_backoff_deterministic_and_bounded():
    a = Backoff(base_s=0.1, cap_s=2.0, seed=3, key="x")
    b = Backoff(base_s=0.1, cap_s=2.0, seed=3, key="x")
    da = [a.delay(i) for i in range(12)]
    db = [b.delay(i) for i in range(12)]
    assert da == db                       # seeded: replays exactly
    assert all(0.05 <= d <= 2.0 for d in da)   # jitter in [0.5,1.0)*nominal
    assert da[0] < 0.1 <= da[4]           # actually grows
    # huge attempts must not overflow (DocStore backoff regression class)
    assert 1.0 <= Backoff(base_s=0.1, cap_s=2.0).delay(5000) <= 2.0


def test_backoff_delay_jitter_bounds():
    """Satellite: the jitter window is exactly [0.5, 1.0) of the capped
    nominal delay, per attempt."""
    b = Backoff(base_s=0.1, cap_s=2.0, seed=9, key="jit")
    for attempt in range(12):
        nominal = min(0.1 * (2 ** attempt), 2.0)
        d = b.delay(attempt)
        assert nominal * 0.5 <= d < nominal, (attempt, d, nominal)
    # negative attempts clamp to the base delay's window
    d = Backoff(base_s=0.2, cap_s=2.0, seed=1).delay(-5)
    assert 0.1 <= d < 0.2


def test_circuit_open_retry_at_monotonic():
    """Satellite: consecutive failures re-open the circuit with
    strictly growing retry_at deadlines (exponential backoff), and the
    refusal carries the live deadline."""
    t = PeerTable("self:0", ["127.0.0.1:9"], fail_threshold=3,
                  backoff_base_s=0.05, backoff_cap_s=60.0, seed=4)
    st = t.peers["127.0.0.1:9"]
    opens = []
    for _ in range(9):
        t._record_failure(st)
        if st.open_until:
            opens.append(st.open_until)
    assert len(opens) == 7          # opens at the 3rd failure
    assert all(b2 > a for a, b2 in zip(opens, opens[1:]))
    with pytest.raises(CircuitOpen) as ei:
        t.call("127.0.0.1:9", "/replicate/ping")
    assert ei.value.peer_id == "127.0.0.1:9"
    assert ei.value.retry_at == st.open_until


def test_call_with_retries_transient_vs_client_error():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    assert call_with_retries(flaky, retries=3,
                             sleep=lambda s: None) == "ok"
    assert len(calls) == 3

    def always_fails():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retries(always_fails, retries=2, sleep=lambda s: None)

    n4xx = []

    def client_error():
        n4xx.append(1)
        raise urllib.error.HTTPError("u", 400, "bad", {}, None)

    with pytest.raises(urllib.error.HTTPError):
        call_with_retries(client_error, retries=3, sleep=lambda s: None)
    assert len(n4xx) == 1                 # 4xx: no retry


def test_fault_injector_deterministic_and_partition():
    a = FaultInjector(seed=11, drop_rate=0.3, dup_rate=0.2)
    b = FaultInjector(seed=11, drop_rate=0.3, dup_rate=0.2)

    def schedule(inj):
        out = []
        for _ in range(40):
            try:
                out.append("dup" if inj.before_call("x", "y") else "ok")
            except FaultDrop:
                out.append("drop")
        return out

    sa, sb = schedule(a), schedule(b)
    assert sa == sb and "drop" in sa and "ok" in sa
    inj = FaultInjector(seed=0)
    inj.partition("a", "b")
    with pytest.raises(FaultDrop):
        inj.before_call("a", "b")
    with pytest.raises(FaultDrop):
        inj.before_call("b", "a")         # partitions are bidirectional
    inj.before_call("a", "c")             # unrelated link unaffected
    inj.heal("a", "b")
    inj.before_call("a", "b")
    assert inj.snapshot()["partition_blocks"] == 2


def test_fault_injector_oneway_partition_latency_skew():
    """Satellite: asymmetric (one-way) partitions, per-link latency
    with jitter, and clock-skew bookkeeping — all in the snapshot."""
    inj = FaultInjector(seed=5)
    inj.partition("a", "b", oneway=True)
    with pytest.raises(FaultDrop):
        inj.before_call("a", "b")       # forward direction cut
    inj.before_call("b", "a")           # reverse still flows
    assert inj.partitioned("a", "b") and not inj.partitioned("b", "a")
    snap = inj.snapshot()
    assert snap["oneway_partitions"] == [["a", "b"]]
    assert snap["partitions"] == [["a", "b"]]
    inj.heal("a", "b")                  # heal clears both directions
    inj.before_call("a", "b")
    assert inj.snapshot()["oneway_partitions"] == []
    # per-link latency is directed and deterministic
    t0 = __import__("time").monotonic()
    inj.set_link_latency("a", "c", 0.01, jitter_s=0.005)
    inj.before_call("a", "c")
    assert __import__("time").monotonic() - t0 >= 0.01
    inj.before_call("c", "a")           # reverse direction: no sleep
    snap = inj.snapshot()
    assert snap["link_delays"] == 1
    assert snap["link_latency"] == {
        "a->c": {"latency_s": 0.01, "jitter_s": 0.005}}
    inj.set_link_latency("a", "c", 0.0)     # zero clears
    assert inj.snapshot()["link_latency"] == {}
    # clock skew is bookkeeping for expiry reasoning, not scheduling
    inj.set_clock_skew("b", 0.75)
    assert inj.now("b") > inj.now("a")
    assert inj.snapshot()["clock_skew"] == {"b": 0.75}
    # identical seeds replay identically with a jittered link enabled
    def schedule(j):
        j.set_link_latency("x", "y", 0.0001, jitter_s=0.0001)
        out = []
        for _ in range(30):
            try:
                out.append(j.before_call("x", "y"))
            except FaultDrop:
                out.append("drop")
        return out
    s1 = schedule(FaultInjector(seed=8, drop_rate=0.3, dup_rate=0.2))
    s2 = schedule(FaultInjector(seed=8, drop_rate=0.3, dup_rate=0.2))
    assert s1 == s2 and "drop" in s1


# ---- unit: ownership -----------------------------------------------------

def test_owner_rendezvous_process_independent():
    hosts = ["127.0.0.1:8001", "127.0.0.1:8002", "127.0.0.1:8003"]
    # pinned: blake2b rendezvous must never drift across processes/PRs
    assert {d: owner_of(d, hosts) for d in
            ("doc-0", "doc-1", "doc-2", "doc-3", "doc-4", "doc-5")} == {
        "doc-0": "127.0.0.1:8001", "doc-1": "127.0.0.1:8001",
        "doc-2": "127.0.0.1:8001", "doc-3": "127.0.0.1:8003",
        "doc-4": "127.0.0.1:8003", "doc-5": "127.0.0.1:8001"}
    # order-independent, and removing a non-owner never moves a doc
    assert owner_of("doc-3", list(reversed(hosts))) == "127.0.0.1:8003"
    assert owner_of("doc-3", ["127.0.0.1:8002", "127.0.0.1:8003"]) \
        == "127.0.0.1:8003"


def test_lease_state_machine_and_takeover():
    a = LeaseManager("hostA", ttl_s=60.0)
    b = LeaseManager("hostB", ttl_s=60.0)
    # desired owner acquires; non-desired host never does
    assert a.ensure_local("d", True)
    assert not b.ensure_local("d", False)
    assert a.get("d").state == ACTIVE and a.get("d").epoch == 1
    # B learns A's live lease -> even as desired owner it must wait
    b.observe_remote("d", "hostA", 1, ACTIVE, ttl_s=60.0)
    assert not b.ensure_local("d", True)
    # ... until the lease expires: takeover bumps the epoch
    b.observe_remote("d", "hostA", 2, ACTIVE, ttl_s=0.0)
    assert b.ensure_local("d", True)
    assert b.get("d").epoch == 3 and b.get("d").holder == "hostB"
    # handoff sender walk: ACTIVE -> GRANTING -> ... -> RELEASED
    epoch = a.begin_handoff("d")
    assert epoch == 2
    assert not a.ensure_local("d", True)     # no merges mid-handoff
    a.abort_handoff("d")
    assert a.ensure_local("d", True)         # rollback restores ACTIVE
    # receiver side: grant is not active until activated
    assert b.accept_grant("e", 5, ttl_s=60.0)
    assert b.get("e").state == GRANTED
    assert not b.ensure_local("e", True)
    assert b.activate_grant("e", 5)
    assert b.activate_grant("e", 5)          # idempotent
    assert not b.activate_grant("e", 4)      # stale epoch refused
    assert b.ensure_local("e", True)


def test_observe_remote_equal_epoch_tie_break():
    """Satellite (bugfix): two differing holders at one epoch resolve
    deterministically and symmetrically — smaller id wins regardless of
    arrival order — and each arbitration is counted."""
    m = ReplicationMetrics()
    c = LeaseManager("hostC", ttl_s=60.0, metrics=m)
    c.observe_remote("d", "hostB", 4, ACTIVE, ttl_s=60.0)
    c.observe_remote("d", "hostA", 4, ACTIVE, ttl_s=60.0)
    assert c.get("d").holder == "hostA"
    assert m.get("leases", "tie_breaks") == 1
    c2 = LeaseManager("hostC", ttl_s=60.0)
    c2.observe_remote("d", "hostA", 4, ACTIVE, ttl_s=60.0)
    c2.observe_remote("d", "hostB", 4, ACTIVE, ttl_s=60.0)
    assert c2.get("d").holder == "hostA"     # opposite order, same pick
    # a peer's echo of OUR lease must never shorten our TTL
    a = LeaseManager("hostA", ttl_s=60.0)
    assert a.ensure_local("x", True)
    exp = a.get("x").expires_at
    a.observe_remote("x", "hostA", 1, ACTIVE, ttl_s=0.0)
    assert a.get("x").expires_at == exp


def test_promise_protocol_exclusive_and_fencing():
    """A voter promises (doc, epoch) to at most one holder ever, and
    every promise raises the fencing floor."""
    m = ReplicationMetrics()
    v = LeaseManager("voter", ttl_s=60.0, metrics=m)
    ok, why = v.promise("d", 3, "hostA")
    assert ok and why == "promised"
    ok, _ = v.promise("d", 3, "hostA")       # same holder: idempotent
    assert ok
    ok, why = v.promise("d", 3, "hostB")     # exclusivity
    assert not ok and why == "promise_conflict"
    assert m.get("quorum", "promise_conflicts") == 1
    ok, why = v.promise("d", 2, "hostB")     # floor is 3 now
    assert not ok and why == "stale_epoch"
    ok, why = v.promise("d", 4, "hostB")     # higher epoch: fresh slot
    assert ok
    assert v.max_epoch_of("d") == 4
    # a live unexpired lease blocks a same-epoch proposer
    v.observe_remote("e", "hostA", 5, ACTIVE, ttl_s=60.0)
    ok, why = v.promise("e", 5, "hostB")
    assert not ok and why == "live_lease"
    # fencing floor revokes a superseded self-held ACTIVE lease
    h = LeaseManager("hostA", ttl_s=60.0, metrics=ReplicationMetrics())
    assert h.ensure_local("f", True) and h.get("f").epoch == 1
    ok, _ = h.promise("f", 9, "hostB")       # we vote for a successor
    assert ok and h.max_epoch_of("f") == 9
    assert not h.ensure_local("f", True)     # revoked, not renewed
    assert h.metrics.get("fencing", "stale_lease_revoked") == 1
    assert h.get("f") is None


def test_replica_journal_persist_restore(tmp_path):
    """Crash-restart durability: floors, promises and held leases
    survive an UNCLOSED journal (WAL replay), a closed one (compacted
    snapshot), and feed LeaseManager.restore so a restarted node never
    re-issues a stale epoch."""
    prefix = str(tmp_path / "rj")
    j = ReplicaJournal(prefix)
    assert not j.has_prior_state()
    j.note_incarnation(3)
    j.note_epoch("d", 7)
    j.note_epoch("d", 5)             # below the floor: deduped
    j.note_promise("d", 7, "hostA")
    j.note_lease("d", "me", 7, "active")
    j.note_lease("e", "me", 2, "active")
    j.drop_lease("e")
    # crash: no close() — reopen replays the WAL
    j2 = ReplicaJournal(prefix)
    assert j2.has_prior_state()
    assert j2.restored_incarnation() == 3
    assert j2.restored_max_epochs() == {"d": 7}
    assert j2.restored_promises() == {
        "d": {"epoch": 7, "holder": "hostA"}}
    assert j2.restored_leases() == {
        "d": {"holder": "me", "epoch": 7, "state": "active"}}
    j2.close()                       # graceful: compacts the snapshot
    j3 = ReplicaJournal(prefix)
    assert j3.restored_max_epochs() == {"d": 7}
    # restore: held lease comes back RELEASED; the next acquisition
    # plans PAST the restored floor (stale-epoch-reissue bugfix)
    lm = LeaseManager("me", ttl_s=60.0)
    lm.restore(j3)
    assert lm.max_epoch_of("d") == 7
    assert lm.get("d").state == RELEASED
    assert lm.ensure_local("d", True)
    assert lm.get("d").epoch == 8
    # ... and the re-acquisition was journaled for the NEXT restart
    j3.close()
    j4 = ReplicaJournal(prefix)
    assert j4.restored_max_epochs()["d"] == 8
    assert j4.restored_leases()["d"]["epoch"] == 8
    j4.close()


def test_membership_states_and_refutation():
    from diamond_types_tpu.replicate.membership import (ALIVE, DEAD,
                                                        LEFT, SUSPECT,
                                                        MembershipView)
    v = MembershipView("a", incarnation=2)
    v.add("b", state=ALIVE)
    v.add("c", state=ALIVE)
    assert v.universe() == ["a", "b", "c"]
    assert v.voters() == ["a", "b", "c"] and v.quorum_size() == 2
    # local health: short outage = SUSPECT, still in the universe
    v.note_health("b", 1.0, dead_after_s=5.0)
    assert v.state_of("b") == SUSPECT and "b" in v.universe()
    # past the takeover delay = DEAD: out of the universe, still a
    # voter (a minority partition cannot shrink the denominator)
    v.note_health("b", 6.0, dead_after_s=5.0)
    assert v.state_of("b") == DEAD
    assert v.universe() == ["a", "c"]
    assert v.voters() == ["a", "b", "c"] and v.quorum_size() == 2
    v.note_health("b", None, dead_after_s=5.0)
    assert v.state_of("b") == ALIVE
    # gossip: higher incarnation wins, equal-incarnation hearsay loses
    v.merge_remote({"b": {"state": DEAD, "incarnation": 0}})
    assert v.state_of("b") == ALIVE
    v.merge_remote({"b": {"state": DEAD, "incarnation": 9}})
    assert v.state_of("b") == DEAD
    # refutation: hearing ourselves SUSPECT bumps our incarnation
    inc = v.self_incarnation
    v.merge_remote({"a": {"state": SUSPECT, "incarnation": inc}})
    assert v.self_incarnation == inc + 1
    assert v.state_of("a") == ALIVE
    # explicit leave: out of BOTH sets; spreads at equal incarnation
    v.leave("c")
    assert v.state_of("c") == LEFT
    assert v.voters() == ["a", "b"] and v.quorum_size() == 2
    v2 = MembershipView("b")
    v2.add("c", state=ALIVE)
    v2.merge_remote(v.gossip_payload())
    assert v2.state_of("c") == LEFT


# ---- integration: two-server smoke (tier-1 gate) -------------------------

def test_two_server_smoke(tmp_path):
    """Two wired servers: ownership proxy routes mutations, anti-entropy
    converges the pair, /metrics exposes replication counters (schema
    v3: latency histograms + derived v2 keys) + the serve schema v4
    fields on both servers."""
    from diamond_types_tpu.tools.server import SyncClient
    httpds, nodes, addrs = _mesh(2, tmp_path)
    try:
        docs = ["alpha", "beta", "gamma"]
        for i, doc in enumerate(docs):
            c = SyncClient(f"http://{addrs[i % 2]}", doc, f"u{i}")
            c.insert(0, f"content of {doc}. ")
            c.sync()
        _step(nodes, rounds=2)
        for doc in docs:
            texts = {_text(a, doc) for a in addrs}
            assert len(texts) == 1, f"{doc} diverged: {texts}"
        # merges ran only on each doc's (unique) lease holder
        for doc in docs:
            mergers = [n.self_id for n in nodes
                       if doc in n.merged_docs]
            assert len(mergers) <= 1
            holder = nodes[0].leases.holder_of(doc)
            if mergers:
                assert mergers == [holder]
        for a in addrs:
            m = _metrics(a)
            assert m["replication"]["version"] == 8
            assert m["replication"]["leases"]["held"] >= 0
            assert m["replication"]["antientropy"]["rounds"] >= 1
            assert "promise_conflicts" in m["replication"]["quorum"]
            assert "rejected_writes" in m["replication"]["fencing"]
            assert m["replication"]["quorum_view"]["quorum"] == 2
            assert not m["replication"]["quorum_view"]["rejoining"]
            assert m["replication"]["membership_view"]["view_version"] >= 1
            # v3: histogram latencies + derived v2 keys
            assert "handoff" in m["replication"]["latencies"]
            assert m["replication"]["handoffs"]["latency_s_total"] >= 0
            assert m["serve"]["version"] == 13
            assert m["serve"]["uptime_s"] >= 0
            assert "denied" in m["serve"]["totals"]
            assert "fenced" in m["serve"]["totals"]
        # ping endpoint serves health probes
        with urllib.request.urlopen(
                f"http://{addrs[0]}/replicate/ping", timeout=5) as r:
            ping = json.loads(r.read())
        assert ping["ok"] and ping["id"] == addrs[0]
    finally:
        _teardown(httpds)


def test_mutation_proxy_routes_to_owner():
    from diamond_types_tpu.tools.server import SyncClient
    httpds, nodes, addrs = _mesh(2, serve_shards=2)
    try:
        doc = "proxied-doc"
        owner = nodes[0].desired_owner(doc)
        other = next(i for i, a in enumerate(addrs) if a != owner)
        c = SyncClient(f"http://{addrs[other]}", doc, "writer")
        c.insert(0, "written at the wrong server")
        c.sync()
        # the push was proxied: the OWNER admitted the merge, the
        # receiving server did not
        owner_node = next(n for n in nodes if n.self_id == owner)
        other_node = next(n for n in nodes if n.self_id != owner)
        assert doc in owner_node.merged_docs
        assert doc not in other_node.merged_docs
        assert other_node.metrics_json()["proxy"]["proxied"] >= 1
        # and the owner actually stores the doc without anti-entropy
        assert "wrong server" in _text(owner, doc)
    finally:
        _teardown(httpds)


def test_explicit_handoff_moves_active_merger():
    from diamond_types_tpu.tools.server import SyncClient
    httpds, nodes, addrs = _mesh(2, serve_shards=2)
    try:
        doc = "handoff-doc"
        owner = nodes[0].desired_owner(doc)
        src = next(n for n in nodes if n.self_id == owner)
        dst = next(n for n in nodes if n.self_id != owner)
        c = SyncClient(f"http://{owner}", doc, "writer")
        c.insert(0, "pre-handoff state")
        c.sync()
        assert src.owns(doc) and not dst.owns(doc)
        epoch_before = src.leases.get(doc).epoch
        assert src.handoff(doc, dst.self_id)
        # dst now holds the ACTIVE lease at a higher epoch; src released
        assert dst.leases.get(doc).state == ACTIVE
        assert dst.leases.get(doc).epoch == epoch_before + 1
        assert dst.owns(doc)
        assert not src.owns(doc)
        # the final patch transfer carried the doc bytes
        assert "pre-handoff" in _text(dst.self_id, doc)
        hm = src.metrics_json()["handoffs"]
        assert hm["completed"] == 1 and hm["latency_s_total"] > 0
    finally:
        _teardown(httpds)


def test_circuit_breaker_opens_and_recovers():
    httpds, nodes, addrs = _mesh(2, serve_shards=0)
    try:
        n0 = nodes[0]
        faults = FaultInjector(seed=1, drop_rate=1.0)   # kill the link
        n0.table.faults = faults
        for _ in range(n0.table.fail_threshold):
            n0.table.probe_once()
        assert not n0.table.is_healthy(addrs[1])
        assert n0.table.healthy_ids() == [addrs[0]]
        st = n0.table.state(addrs[1])
        assert st["circuit_open"] and st["consecutive_failures"] >= 3
        # ownership does NOT reassign while the outage is shorter than
        # the takeover delay (a short partition must not create a
        # second self-appointed owner) ...
        assert n0.takeover_after_s == 5.0     # defaults to lease TTL
        assert n0.ownership_ids() == sorted(addrs)
        # ... but once the holder's lease has provably expired, the
        # docs collapse onto the lone healthy host
        n0.takeover_after_s = 0.0
        assert n0.ownership_ids() == [addrs[0]]
        assert n0.desired_owner("any-doc") == addrs[0]
        n0.takeover_after_s = 5.0
        # heal: backoff window must lapse before the half-open probe
        n0.table.faults = None
        deadline = __import__("time").monotonic() + 10
        while not n0.table.is_healthy(addrs[1]):
            n0.table.probe_once()
            assert __import__("time").monotonic() < deadline
        assert n0.table.state(addrs[1])["consecutive_failures"] == 0
        m = n0.metrics_json()["probes"]
        assert m["circuit_opens"] == 1 and m["circuit_closes"] == 1
    finally:
        _teardown(httpds)


def test_peer_down_duration_across_probe_recovery():
    """Satellite: down_duration is None while healthy, grows while the
    circuit stays open, and returns to None once the probe loop
    recovers the peer."""
    import time
    httpds, nodes, addrs = _mesh(2, serve_shards=0)
    try:
        t = nodes[0].table
        peer = addrs[1]
        assert t.down_duration(peer) is None       # never failed
        assert t.down_duration(t.self_id) is None  # self: always None
        assert t.down_duration("unknown:1") == float("inf")
        t.probe_once()
        assert t.down_duration(peer) is None       # healthy probe
        t.faults = FaultInjector(seed=2, drop_rate=1.0)
        for _ in range(t.fail_threshold):
            t.probe_once()
        d1 = t.down_duration(peer)
        assert d1 is not None and d1 >= 0.0
        time.sleep(0.02)
        assert t.down_duration(peer) > d1          # grows while down
        # pinned `now` makes the duration arithmetic exact
        st = t.peers[peer]
        assert t.down_duration(peer, now=st.down_since + 1.5) == 1.5
        t.faults = None
        deadline = time.monotonic() + 10
        while t.down_duration(peer) is not None:   # recovery clears it
            t.probe_once()
            assert time.monotonic() < deadline
        assert t.is_healthy(peer)
    finally:
        _teardown(httpds)


def test_syncclient_retries_transient_failures(monkeypatch):
    """Satellite: SyncClient survives transient connection failures on
    pull/push via the shared backoff helper."""
    from diamond_types_tpu.tools import server as srv
    httpd = srv.serve(port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        real_urlopen = urllib.request.urlopen
        fail = {"n": 2}

        def flaky_urlopen(req, timeout=None):
            if fail["n"] > 0:
                fail["n"] -= 1
                raise ConnectionResetError("injected")
            return real_urlopen(req, timeout=timeout)

        monkeypatch.setattr(srv.urllib.request, "urlopen",
                            flaky_urlopen)
        c = srv.SyncClient(f"http://127.0.0.1:{port}", "retry-doc",
                           "amy", retries=3)
        c.insert(0, "survives flaky transport")
        c.sync()                      # would raise without retry
        fail["n"] = 2
        c.pull()
        assert c.text() == "survives flaky transport"
        # retries exhausted -> the error still surfaces
        fail["n"] = 99
        c.insert(0, "x")
        with pytest.raises(OSError):
            c.push()
    finally:
        _teardown([httpd])


# ---- acceptance: convergence under faults --------------------------------

def test_convergence_under_faults(tmp_path):
    """ISSUE acceptance: two in-process servers with injected faults
    (drops + a healed partition, fixed seed) end byte-identical on
    every doc, each doc's merges ran only on its lease holder, and
    GET /metrics exposes the replication counters on both servers."""
    from diamond_types_tpu.tools.server import SyncClient
    faults = FaultInjector(seed=1234, drop_rate=0.25, dup_rate=0.1)
    httpds, nodes, addrs = _mesh(2, tmp_path, serve_shards=2,
                                 faults=faults)
    try:
        docs = ["conv-0", "conv-1", "conv-2"]
        clients = {(i, d): SyncClient(f"http://{addrs[i]}", d,
                                      f"w{i}-{d}", retries=1)
                   for i in range(2) for d in docs}

        def edit(i, d, text):
            c = clients[(i, d)]
            try:
                c.pull()
            except OSError:
                pass
            c.insert(0, text)
            try:
                c.sync()
            except OSError:
                pass          # dropped mid-fault; reconciled later

        for i, d in [(0, docs[0]), (1, docs[1]), (0, docs[2])]:
            edit(i, d, f"seed {d}. ")
        _step(nodes)
        # partition the pair; both sides keep writing every doc
        faults.partition(addrs[0], addrs[1])
        for r in range(3):
            for d in docs:
                edit(0, d, f"left{r} ")
                edit(1, d, f"right{r} ")
            _step(nodes)
        faults.heal()
        # reconcile to convergence (bounded; fixed seed keeps it
        # tight). Paced so breaker backoff windows opened during the
        # partition can lapse between rounds.
        import time
        for _ in range(10):
            time.sleep(0.06)
            _step(nodes)
            if all(len({_text(a, d) for a in addrs}) == 1
                   for d in docs):
                break
        for d in docs:
            texts = {a: _text(a, d) for a in addrs}
            assert len(set(texts.values())) == 1, \
                f"{d} diverged: {texts}"
            assert "left" in texts[addrs[0]] \
                and "right" in texts[addrs[0]]
        # owner-only merges: at most one host ever admitted each doc
        for d in docs:
            mergers = [n.self_id for n in nodes if d in n.merged_docs]
            assert len(mergers) <= 1, f"{d} merged on {mergers}"
            if mergers:
                assert mergers[0] == nodes[0].desired_owner(d)
        # both servers expose the replication counters, and the fault
        # schedule actually exercised the mesh
        for a in addrs:
            rm = _metrics(a)["replication"]
            assert rm["antientropy"]["rounds"] >= 4
            assert rm["faults"]["drops"] >= 1
        assert faults.snapshot()["partition_blocks"] >= 1
    finally:
        _teardown(httpds)


def test_wire_mesh_frames_and_prom(tmp_path):
    """ISSUE 16: a wire-v1 pair converges with binary frames actually
    on the wire — per-channel counters land in /metrics (replication
    schema v7 "wire" group) and render as dt_wire_* prom families."""
    from diamond_types_tpu.tools.server import SyncClient
    httpds, nodes, addrs = _mesh(2, tmp_path)
    try:
        for i, doc in enumerate(["wire-a", "wire-b"]):
            c = SyncClient(f"http://{addrs[i]}", doc, f"w{i}")
            c.insert(0, f"framed content of {doc}. ")
            c.sync()
        _step(nodes, rounds=3)
        for doc in ("wire-a", "wire-b"):
            texts = {_text(a, doc) for a in addrs}
            assert len(texts) == 1, f"{doc} diverged: {texts}"
        # frames actually flowed: the docs listing + summary GETs are
        # framed from round one (header negotiation), so every node
        # both sent bytes and framed some of them
        wires = [_metrics(a)["replication"]["wire"] for a in addrs]
        assert all(w["antientropy_bytes_sent"] > 0 for w in wires)
        assert sum(w["antientropy_frames"] for w in wires) > 0
        assert sum(w["gossip_bytes_sent"] for w in wires) > 0
        for w in wires:
            assert w["antientropy_bytes_saved"] >= 0
        # prom rendering: labeled dt_wire_* families on both servers
        with urllib.request.urlopen(
                f"http://{addrs[0]}/metrics?format=prom",
                timeout=5) as r:
            prom = r.read().decode("utf8")
        assert 'dt_wire_bytes_sent_total{channel="antientropy"}' in prom
        assert 'dt_wire_frames_total{channel="proxy"}' in prom
    finally:
        _teardown(httpds)


def test_mixed_version_mesh_converges_on_json(tmp_path):
    """ISSUE 16 acceptance: a mixed-version mesh — one wire-v1 node,
    one JSON-pinned node emulating an old build mid-rolling-upgrade —
    converges byte-identically. The pinned node never advertises the
    capability (ping gossip) or the request header, so NO frames flow
    in either direction; both sides still account bytes_sent."""
    import threading as _threading

    from diamond_types_tpu.tools.server import SyncClient, serve
    httpds, addrs = [], []
    for i in range(2):
        httpd = serve(port=0, data_dir=str(tmp_path / f"s{i}"),
                      serve_shards=2)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            backoff_base_s=0.01, backoff_cap_s=0.05,
            wire_enabled=(i == 0)))
        _threading.Thread(target=httpd.serve_forever,
                          daemon=True).start()
    try:
        assert nodes[0].wire.enabled and not nodes[1].wire.enabled
        doc = "mixed"
        c0 = SyncClient(f"http://{addrs[0]}", doc, "alice")
        c0.insert(0, "héllo ")
        c0.sync()
        c1 = SyncClient(f"http://{addrs[1]}", doc, "bob")
        c1.pull()
        c1.insert(len(c1.text()), "wörld ")
        c1.sync()
        _step(nodes, rounds=3)
        texts = {_text(a, doc) for a in addrs}
        assert len(texts) == 1, f"diverged: {texts}"
        # negotiation held: the old peer never saw (or sent) a frame
        w0 = nodes[0].metrics.wire_counters()
        w1 = nodes[1].metrics.wire_counters()
        for ch in ("antientropy", "proxy", "hydrate", "gossip"):
            assert w0[f"{ch}_frames"] == 0, (ch, w0)
            assert w1[f"{ch}_frames"] == 0, (ch, w1)
        # ...but transport accounting stayed on for both builds
        assert w0["antientropy_bytes_sent"] > 0
        assert w1["antientropy_bytes_sent"] > 0
        assert not nodes[0].wire.use_wire(addrs[1])
    finally:
        _teardown(httpds)
