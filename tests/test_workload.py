"""Workload-harness tests: seeded statistical bounds for the arrival
and popularity samplers, the tier-1 smoke scenario's scorecard, and
the scorecard-diff regression gate."""

import copy
import json
import math

import pytest

from diamond_types_tpu.obs import Observability
from diamond_types_tpu.obs.prom import render_metrics
from diamond_types_tpu.obs.scorecard import (SCORECARD_VERSION, Band,
                                             diff_scorecards,
                                             last_scenario,
                                             publish_scenario)
from diamond_types_tpu.serve.metrics import HYDRATION_KEYS, ServeMetrics
from diamond_types_tpu.tools import cli
from diamond_types_tpu.workload import (SCENARIOS, Bursty, HotSetRotation,
                                        Poisson, Ramp, Zipf)
from diamond_types_tpu.workload.runner import _build_events

pytestmark = pytest.mark.scenario


# ---- arrival processes ---------------------------------------------------

def test_poisson_rate_and_interarrival_quantiles():
    rate, dur = 50.0, 100.0
    times = Poisson(rate, seed=3).schedule(dur)
    # count within 4 sigma of rate*dur (Poisson sd = sqrt(n))
    expect = rate * dur
    assert abs(len(times) - expect) < 4 * math.sqrt(expect)
    gaps = [b - a for a, b in zip(times, times[1:])]
    mean = sum(gaps) / len(gaps)
    assert abs(mean - 1.0 / rate) < 0.15 / rate
    # exponential median = ln2/rate
    p50 = sorted(gaps)[len(gaps) // 2]
    assert abs(p50 - math.log(2) / rate) < 0.2 * math.log(2) / rate
    assert times == sorted(times)
    assert all(0.0 <= t < dur for t in times)


def test_poisson_schedule_deterministic():
    a = Poisson(20.0, seed=9).schedule(30.0)
    b = Poisson(20.0, seed=9).schedule(30.0)
    assert a == b                       # byte-identical across runs
    proc = Poisson(20.0, seed=9)
    assert proc.schedule(30.0) == a     # and across calls
    assert Poisson(20.0, seed=10).schedule(30.0) != a


def test_bursty_flash_crowd_concentration():
    proc = Bursty(base_per_s=10.0, burst_x=10.0, every_s=10.0,
                  burst_len_s=2.0, seed=5)
    times = proc.schedule(100.0)
    in_burst = sum(1 for t in times if proc.in_burst(t))
    out = len(times) - in_burst
    # burst windows are 20% of the clock at 10x rate: per-second
    # intensity in-burst must dominate by far more than the window
    # ratio alone (100/20 vs 100/80 normalizes the unequal spans)
    assert (in_burst / 20.0) > 5 * (out / 80.0)
    assert proc.schedule(100.0) == times


def test_ramp_shifts_mass_late():
    times = Ramp(start_per_s=0.0, end_per_s=50.0, ramp_s=50.0,
                 seed=2).schedule(50.0)
    early = sum(1 for t in times if t < 25.0)
    late = len(times) - early
    # linear 0->50 puts 3x the mass in the second half
    assert late > 2 * early


# ---- popularity laws -----------------------------------------------------

def test_zipf_frequency_ranks():
    n, draws = 40, 30_000
    law = Zipf(n, s=1.1, seed=4)
    picks = law.draws([0.0] * draws)
    counts = [0] * n
    for d in picks:
        counts[d] += 1
    # monotone head: rank order matches weight order
    assert counts[0] > counts[3] > counts[10] > counts[30]
    # head frequency within 25% of the law's own weight
    assert abs(counts[0] / draws - law.weight(0)) < 0.25 * law.weight(0)
    assert picks == law.draws([0.0] * draws)     # deterministic
    assert picks != Zipf(n, s=1.1, seed=5).draws([0.0] * draws)


def test_hotset_rotation_concentrates_and_rotates():
    law = HotSetRotation(100, hot_k=2, hot_weight=0.9,
                         rotate_every_s=1000.0, seed=6)
    picks = law.draws([0.0] * 5_000)
    hot = set(law.hot_set(0.0))
    frac = sum(1 for d in picks if d in hot) / len(picks)
    assert frac > 0.8                   # 0.9 weight + uniform residue
    # a later epoch draws a different seeded hot set
    rotating = HotSetRotation(100, hot_k=2, rotate_every_s=1.0, seed=6)
    sets = {tuple(rotating.hot_set(float(e))) for e in range(8)}
    assert len(sets) > 1


def test_event_tape_deterministic():
    sc = SCENARIOS["smoke"]
    assert _build_events(sc) == _build_events(sc)


# ---- registry ------------------------------------------------------------

def test_registry_has_smoke_and_bank_churn():
    assert "smoke" in SCENARIOS
    assert not SCENARIOS["smoke"].slow
    bank = SCENARIOS["bank-churn-1m"]
    assert bank.slow
    assert bank.bank["docs"] == 1_000_000
    assert bank.bank["warm_slots"] == 10_000


# ---- spill counters (PR 8 residual) --------------------------------------

def test_hydration_keys_include_spill_counters():
    assert "spills_to_snapshot" in HYDRATION_KEYS
    assert "spill_bytes" in HYDRATION_KEYS


def test_prom_spill_families_zero_filled_when_idle():
    m = ServeMetrics(n_shards=1, flush_docs=4, max_pending=16)
    text = render_metrics({"serve": m.snapshot()})
    assert "dt_serve_hydration_spills_to_snapshot_total 0" in text
    assert "dt_serve_hydration_spill_bytes_total 0" in text


# ---- the smoke scenario + scorecard (acceptance pins) --------------------

@pytest.fixture(scope="module")
def smoke_card_path(tmp_path_factory):
    out = tmp_path_factory.mktemp("scorecards") / "smoke.json"
    rc = cli.main(["scenario", "run", "--name", "smoke",
                   "--out", str(out)])
    assert rc == 0
    return out


def test_smoke_scorecard_complete(smoke_card_path):
    card = json.loads(smoke_card_path.read_text())
    assert card["version"] == SCORECARD_VERSION
    assert card["scenario"]["name"] == "smoke"
    assert card["throughput"]["ops_per_s"] > 0
    for k in ("flush", "read", "visibility"):
        assert isinstance(card["latency_p99_s"][k], float)
    assert card["latency_p99_s"]["read"] > 0
    # burn-minutes zero-filled per objective on a healthy run
    for name in ("flush_p99", "read_staleness_p99", "visibility_p99"):
        assert card["burn_minutes"][name] == 0.0
    assert card["convergence"]["converged"] is True
    # per-peer convergence lag populated (owner side tracks journeys)
    lags = [row for peers in card["convergence"]["lag"].values()
            for row in peers.values()]
    assert lags and all(r["n"] > 0 for r in lags)
    assert card["bytes_per_op"] > 0
    # device-tier spill accounting stamped into the scorecard: the
    # smoke bank lane (48 docs / 8 slots) must actually spill
    assert card["hydration"]["spills_to_snapshot"] > 0
    assert card["hydration"]["spill_bytes"] > 0
    assert card["totals"]["errors"] == 0
    assert card["ok"] is True


def test_scorecard_diff_self_compare_passes(smoke_card_path):
    p = str(smoke_card_path)
    assert cli.main(["scorecard-diff", p, p, "--gate"]) == 0


@pytest.mark.parametrize("mutate", [
    lambda c: c["latency_p99_s"].__setitem__(
        "flush", (c["latency_p99_s"]["flush"] or 0) * 10 + 1.0),
    lambda c: c["throughput"].__setitem__(
        "ops_per_s", c["throughput"]["ops_per_s"] * 0.3),
    lambda c: c["totals"].__setitem__("errors", 3),
    lambda c: c["convergence"].__setitem__("converged", False),
])
def test_scorecard_diff_gates_on_perturbation(smoke_card_path,
                                              tmp_path, mutate):
    card = json.loads(smoke_card_path.read_text())
    mutate(card)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(card))
    p = str(smoke_card_path)
    assert cli.main(["scorecard-diff", p, str(bad), "--gate"]) == 1
    # without --gate the diff is informational: always exit 0
    assert cli.main(["scorecard-diff", p, str(bad)]) == 0


def test_scorecard_diff_missing_metric_never_gates(smoke_card_path,
                                                   tmp_path):
    card = json.loads(smoke_card_path.read_text())
    del card["hydration"]["spills_to_snapshot"]
    trimmed = tmp_path / "trimmed.json"
    trimmed.write_text(json.dumps(card))
    assert cli.main(["scorecard-diff", str(smoke_card_path),
                     str(trimmed), "--gate"]) == 0


def test_band_absolute_slack_floors_relative():
    band = Band("lower", rel=0.5, abs_=0.01)
    assert band.allows(0.001, 0.009)    # inside abs slack
    assert not band.allows(0.001, 0.10)
    assert band.allows(10.0, 14.0)      # inside rel band
    assert not band.allows(10.0, 16.0)
    up = Band("higher", rel=0.3, abs_=0.0)
    assert up.allows(100.0, 80.0)
    assert not up.allows(100.0, 60.0)
    assert up.allows(100.0, 500.0)      # improvement always passes


def test_diff_engine_rows_and_regressions(smoke_card_path):
    card = json.loads(smoke_card_path.read_text())
    worse = copy.deepcopy(card)
    worse["bytes_per_op"] = card["bytes_per_op"] * 3 + 1000
    diff = diff_scorecards(card, worse)
    assert not diff["ok"]
    assert diff["regressions"] == ["bytes_per_op"]
    self_diff = diff_scorecards(card, card)
    assert self_diff["ok"] and not self_diff["regressions"]


# ---- live snapshot -> obs (the obs-watch scenario panel feed) ------------

def test_published_scenario_rides_obs_snapshot():
    prev = last_scenario()
    try:
        publish_scenario({"name": "smoke", "phase": "traffic",
                          "tick": 3, "ticks": 6, "verdict": "slo=ok"})
        snap = Observability(enabled=False).snapshot()
        assert snap["scenario"]["name"] == "smoke"
        assert snap["scenario"]["phase"] == "traffic"
        publish_scenario(None)
        assert "scenario" not in Observability(enabled=False).snapshot()
    finally:
        publish_scenario(prev)
