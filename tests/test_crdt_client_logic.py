"""Differential test of the in-browser CRDT engine's ALGORITHM.

The engine is SINGLE-SOURCED (VERDICT r4 #5): the replay algorithm lives
in diamond_types_tpu/tools/crdt_replay_src.py, which this suite executes
directly AND which web_assets transpiles to the shipped JS at import
time (tools/py2js.py; an out-of-subset edit fails generation). There is
no hand-written mirror left to drift — the code fuzzed here IS the code
the browser runs, modulo the mechanical transpilation mapping documented
in py2js's header.
"""

import random

import pytest

from diamond_types_tpu import OpLog
from diamond_types_tpu.tools.crdt_replay_src import replay as _replay_mirror
from diamond_types_tpu.tools.server import _crdt_apply_op


def _oracle_text(ops):
    ol = OpLog()
    # Feed in topo order, gated ALSO on per-agent seq contiguity: the
    # server protocol receives each client's stream in seq order even
    # when seq order is not causal order (same-agent concurrency, e.g.
    # git imports), and _crdt_apply_op rejects seq gaps.
    done = set()
    next_seq = {}
    rest = list(ops)
    while rest:
        progressed = False
        nxt = []
        for o in sorted(rest, key=lambda o: (o["agent"], o["seq"])):
            if o["seq"] != next_seq.get(o["agent"], 0):
                nxt.append(o)
                continue
            if all((a, s) in done for (a, s) in o["parents"]):
                row = {"agent": o["agent"], "seq": o["seq"],
                       "parents": o["parents"], "kind": o["kind"],
                       "pos": o["pos"]}
                if o["kind"] == "ins":
                    row["content"] = o["ch"]
                else:
                    row["len"] = 1
                _crdt_apply_op(ol, row)
                done.add((o["agent"], o["seq"]))
                next_seq[o["agent"]] = o["seq"] + 1
                progressed = True
            else:
                nxt.append(o)
        assert progressed
        rest = nxt
    return ol.checkout_tip().snapshot()


ALPHABET = "abcdefgh XY12\u00a9\u0394\u2190\U00010190"  # incl. BMP + astral


@pytest.mark.parametrize("seed", range(30))
def test_browser_engine_vs_oracle(seed):
    """Random concurrent unit-op histories: the browser replay algorithm
    must converge to EXACTLY the oplog engines' text."""
    rng = random.Random(4400 + seed)
    agents = ["anna", "bert", "cleo"]
    ops = []
    heads = {}     # agent -> (frontier, text)
    shared_frontier, shared_text = [], ""
    for a in agents:
        heads[a] = ([], "")
    for step in range(40):
        a = agents[rng.randrange(3)]
        frontier, text = heads[a]
        seq = sum(1 for o in ops if o["agent"] == a)
        if not text or rng.random() < 0.7:
            pos = rng.randint(0, len(text))
            ch = rng.choice(ALPHABET)
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "ins", "pos": pos, "ch": ch})
            text = text[:pos] + ch + text[pos:]
        else:
            pos = rng.randrange(len(text))
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "del", "pos": pos, "ch": None})
            text = text[:pos] + text[pos + 1:]
        heads[a] = ([[a, seq]], text)
        if rng.random() < 0.3:
            # peer pulls everything known so far (frontier = all heads)
            f = []
            for a2 in agents:
                s2 = sum(1 for o in ops if o["agent"] == a2)
                if s2:
                    f.append([a2, s2 - 1])
            merged = _replay_mirror(ops)
            heads[a] = (f, merged)
    got = _replay_mirror(ops)
    exp = _oracle_text(ops)
    assert got == exp, f"seed {seed}: {got!r} != {exp!r}"


def _golden_fixture():
    import json
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "crdt_client_golden.json")
    with open(path) as f:
        return json.load(f)


def test_golden_vectors_mirror():
    """Every golden conformance vector replays to its oracle-blessed text
    through the Python mirror (vectors cover same-gap concurrency,
    doc-end ties, same-agent branches and scanning-rollback shapes;
    generated + oracle-verified by tests/gen_crdt_golden.py)."""
    fx = _golden_fixture()
    assert len(fx["vectors"]) >= 40
    for v in fx["vectors"]:
        got = _replay_mirror(v["ops"])
        assert got == v["expect"], \
            f"vector {v['name']}: {got!r} != {v['expect']!r}"


def test_golden_fixture_pins_engine_source():
    """Drift detection: the fixture records the sha256 of the SINGLE
    SOURCE (crdt_replay_src.py) it was blessed against. If this fails,
    the engine algorithm changed: re-run the oracle blessing and
    regenerate with python -m tests.gen_crdt_golden. (The shipped JS
    cannot drift independently — it is generated from this source at
    import time; hand-editing it is impossible.)"""
    import hashlib
    import inspect

    from diamond_types_tpu.tools import crdt_replay_src
    fx = _golden_fixture()
    cur = hashlib.sha256(
        inspect.getsource(crdt_replay_src).encode("utf8")).hexdigest()
    assert cur == fx["src_sha256"], (
        "crdt_replay_src.py drifted from the golden fixture — see this "
        "test's docstring for the regen steps")


def test_conformance_runner_embeds_shipped_js():
    """The node runner must contain the engine source verbatim — it IS
    the executable form of the shipped JS for environments with a JS
    runtime (none exists in this image)."""
    import os
    from diamond_types_tpu.tools.web_assets import crdt_engine_js
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "crdt_conformance.mjs")
    with open(path) as f:
        runner = f.read()
    assert crdt_engine_js() in runner

def test_transpiler_rejects_out_of_subset_source(tmp_path):
    """The generation-time assertion: an engine edit outside the
    transpilable subset must fail loudly, not ship silently-wrong JS."""
    import importlib.util

    from diamond_types_tpu.tools.py2js import (UnsupportedConstruct,
                                               transpile_module)
    path = tmp_path / "bad_engine.py"
    path.write_text("def replay(ops):\n"
                    "    return [o for o in ops]  # comprehension\n")
    spec = importlib.util.spec_from_file_location("bad_engine", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with pytest.raises(UnsupportedConstruct):
        transpile_module(mod)


def test_astral_agent_names_rejected_at_edge():
    """Agent ordering is a convergence tie-break; JS compares UTF-16
    units, Python code points, and they diverge exactly on astral
    chars — so the server edge rejects astral agent names (the single
    source's documented precondition, now enforced)."""
    from diamond_types_tpu.tools.server import _agent_name_ok
    assert _agent_name_ok("anna")
    assert _agent_name_ok("ﬀligature")     # BMP is fine
    assert not _agent_name_ok("\U0001F600grin")  # astral: rejected
    assert not _agent_name_ok("")
    assert not _agent_name_ok(None)
    with pytest.raises(ValueError, match="bad agent name"):
        _crdt_apply_op(OpLog(), {"agent": "\U0001F600", "seq": 0,
                                 "parents": [], "kind": "ins", "pos": 0,
                                 "content": "x"})


def test_page_embeds_generated_engine():
    """The editor page carries the transpiled engine verbatim, and the
    legacy hand-written replay is gone — the generated function is the
    only replay in the page."""
    from diamond_types_tpu.tools.web_assets import CRDT_HTML, crdt_engine_js
    js = crdt_engine_js()
    assert js in CRDT_HTML
    assert CRDT_HTML.count("function replay(") == 1
    assert "replay(eng.ops)" in CRDT_HTML


def test_astral_agent_patch_rejected_on_push(tmp_path):
    """The BINARY push path enforces the same agent-name rules as the
    JSON paths — a patch registering an astral-named agent is rejected
    before decode_into can poison the doc."""
    import threading
    import urllib.error
    import urllib.request

    from diamond_types_tpu.encoding.encode import encode_oplog
    from diamond_types_tpu.text.crdt import ListCRDT
    from diamond_types_tpu.tools.server import serve
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        c = ListCRDT()
        ag = c.get_or_create_agent_id("\U0001F600grin")
        c.insert(ag, 0, "astral")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/doc/p/push", encode_oplog(c.oplog)))
        assert ei.value.code == 400
        with urllib.request.urlopen(base + "/doc/p") as r:
            assert r.read() == b""       # nothing applied
    finally:
        httpd.shutdown()


def test_transpiler_rejects_chained_assignment(tmp_path):
    import importlib.util

    from diamond_types_tpu.tools.py2js import (UnsupportedConstruct,
                                               transpile_module)
    path = tmp_path / "chain_engine.py"
    path.write_text("def replay(ops):\n"
                    "    a = b = len(ops)\n"
                    "    return a\n")
    spec = importlib.util.spec_from_file_location("chain_engine", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with pytest.raises(UnsupportedConstruct):
        transpile_module(mod)
