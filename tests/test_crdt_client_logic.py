"""Differential test of the in-browser CRDT engine's ALGORITHM.

No JS runtime exists in this image, so `_replay_mirror` below is a
line-faithful Python transliteration of web_assets.CRDT_HTML's replay()
(same structure: topological order with (agent, seq) ties, ancestor
sets, origin resolution, the YjsMod integrate state machine with the
scanning rollback). Fuzzing it against the real oplog engines validates
the browser algorithm; keep the two in sync when editing either.
"""

import random

import pytest

from diamond_types_tpu import OpLog
from diamond_types_tpu.tools.server import _crdt_apply_op


def _replay_mirror(ops):
    by_key = {(o["agent"], o["seq"]): i for i, o in enumerate(ops)}
    n = len(ops)
    # topological order, ready set sorted by (agent, seq)
    indeg = [0] * n
    kids = {}
    for i, o in enumerate(ops):
        for (a, s) in o["parents"]:
            j = by_key[(a, s)]
            indeg[i] += 1
            kids.setdefault(j, []).append(i)
    ready = sorted((i for i in range(n) if not indeg[i]),
                   key=lambda i: (ops[i]["agent"], ops[i]["seq"]))
    order = []
    while ready:
        ready.sort(key=lambda i: (ops[i]["agent"], ops[i]["seq"]))
        i = ready.pop(0)
        order.append(i)
        for k in kids.get(i, ()):
            indeg[k] -= 1
            if not indeg[k]:
                ready.append(k)
    assert len(order) == n

    anc = [set() for _ in range(n)]
    for i in order:
        for (a, s) in ops[i]["parents"]:
            j = by_key[(a, s)]
            anc[i] |= anc[j]
            anc[i].add(j)

    items = []   # dicts: ins, dels, ol, a, s, ch, orrItem, orrKey

    def in_anc(i, it):
        return it["ins"] in anc[i]

    def visible_at(i, it):
        return in_anc(i, it) and not any(d in anc[i] for d in it["dels"])

    for i in order:
        op = ops[i]
        if op["kind"] == "del":
            seen = 0
            for it in items:
                if visible_at(i, it):
                    if seen == op["pos"]:
                        it["dels"].append(i)
                        break
                    seen += 1
            continue
        ol_idx, seen = -1, 0
        if op["pos"] > 0:
            for x, it in enumerate(items):
                if visible_at(i, it):
                    seen += 1
                    if seen == op["pos"]:
                        ol_idx = x
                        break
        orr_idx = len(items)
        for x in range(ol_idx + 1, len(items)):
            if in_anc(i, items[x]):
                orr_idx = x
                break
        dst, scanning, scan_start = ol_idx + 1, False, ol_idx + 1
        my_orr_key = ((items[orr_idx]["a"], items[orr_idx]["s"])
                      if orr_idx < len(items) else "END")
        for x in range(ol_idx + 1, orr_idx):
            o = items[x]
            if o["ol"] < ol_idx:
                break
            if o["ol"] == ol_idx:
                if o["orrKey"] == my_orr_key:
                    ins_here = (op["agent"], op["seq"]) < (o["a"], o["s"])
                    if ins_here:
                        break
                    scanning = False
                else:
                    o_r = float("inf") if o["orrItem"] == -1 else o["orrItem"]
                    my_r = float("inf") if orr_idx >= len(items) else orr_idx
                    if o_r < my_r:
                        # rollback lands BEFORE this item (merge.rs:233
                        # clones the cursor before advancing past it)
                        if not scanning:
                            scanning, scan_start = True, x
                    else:
                        scanning = False
            dst = x + 1
        if scanning:
            dst = scan_start
        item = {"ins": i, "dels": [], "ol": ol_idx, "a": op["agent"],
                "s": op["seq"], "ch": op["ch"],
                "orrItem": -1 if orr_idx >= len(items) else orr_idx,
                "orrKey": my_orr_key}
        for it in items:
            if it["ol"] >= dst:
                it["ol"] += 1
            if it["orrItem"] != -1 and it["orrItem"] >= dst:
                it["orrItem"] += 1
        if item["ol"] >= dst:
            item["ol"] += 1
        if item["orrItem"] != -1 and item["orrItem"] >= dst:
            item["orrItem"] += 1
        items.insert(dst, item)
    return "".join(it["ch"] for it in items if not it["dels"])


def _oracle_text(ops):
    ol = OpLog()
    # Feed in topo order, gated ALSO on per-agent seq contiguity: the
    # server protocol receives each client's stream in seq order even
    # when seq order is not causal order (same-agent concurrency, e.g.
    # git imports), and _crdt_apply_op rejects seq gaps.
    done = set()
    next_seq = {}
    rest = list(ops)
    while rest:
        progressed = False
        nxt = []
        for o in sorted(rest, key=lambda o: (o["agent"], o["seq"])):
            if o["seq"] != next_seq.get(o["agent"], 0):
                nxt.append(o)
                continue
            if all((a, s) in done for (a, s) in o["parents"]):
                row = {"agent": o["agent"], "seq": o["seq"],
                       "parents": o["parents"], "kind": o["kind"],
                       "pos": o["pos"]}
                if o["kind"] == "ins":
                    row["content"] = o["ch"]
                else:
                    row["len"] = 1
                _crdt_apply_op(ol, row)
                done.add((o["agent"], o["seq"]))
                next_seq[o["agent"]] = o["seq"] + 1
                progressed = True
            else:
                nxt.append(o)
        assert progressed
        rest = nxt
    return ol.checkout_tip().snapshot()


ALPHABET = "abcdefgh XY12\u00a9\u0394\u2190\U00010190"  # incl. BMP + astral


@pytest.mark.parametrize("seed", range(30))
def test_browser_engine_vs_oracle(seed):
    """Random concurrent unit-op histories: the browser replay algorithm
    must converge to EXACTLY the oplog engines' text."""
    rng = random.Random(4400 + seed)
    agents = ["anna", "bert", "cleo"]
    ops = []
    heads = {}     # agent -> (frontier, text)
    shared_frontier, shared_text = [], ""
    for a in agents:
        heads[a] = ([], "")
    for step in range(40):
        a = agents[rng.randrange(3)]
        frontier, text = heads[a]
        seq = sum(1 for o in ops if o["agent"] == a)
        if not text or rng.random() < 0.7:
            pos = rng.randint(0, len(text))
            ch = rng.choice(ALPHABET)
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "ins", "pos": pos, "ch": ch})
            text = text[:pos] + ch + text[pos:]
        else:
            pos = rng.randrange(len(text))
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "del", "pos": pos, "ch": None})
            text = text[:pos] + text[pos + 1:]
        heads[a] = ([[a, seq]], text)
        if rng.random() < 0.3:
            # peer pulls everything known so far (frontier = all heads)
            f = []
            for a2 in agents:
                s2 = sum(1 for o in ops if o["agent"] == a2)
                if s2:
                    f.append([a2, s2 - 1])
            merged = _replay_mirror(ops)
            heads[a] = (f, merged)
    got = _replay_mirror(ops)
    exp = _oracle_text(ops)
    assert got == exp, f"seed {seed}: {got!r} != {exp!r}"


def _golden_fixture():
    import json
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "crdt_client_golden.json")
    with open(path) as f:
        return json.load(f)


def test_golden_vectors_mirror():
    """Every golden conformance vector replays to its oracle-blessed text
    through the Python mirror (vectors cover same-gap concurrency,
    doc-end ties, same-agent branches and scanning-rollback shapes;
    generated + oracle-verified by tests/gen_crdt_golden.py)."""
    fx = _golden_fixture()
    assert len(fx["vectors"]) >= 40
    for v in fx["vectors"]:
        got = _replay_mirror(v["ops"])
        assert got == v["expect"], \
            f"vector {v['name']}: {got!r} != {v['expect']!r}"


def test_golden_fixture_pins_js_engine():
    """Drift detection (VERDICT r3 missing #3): the fixture records the
    sha256 of the EXACT shipped JS engine text it was generated against.
    If this fails, the browser engine changed: re-validate the mirror
    against the new JS, run the vectors through a real JS runtime
    (node tests/data/crdt_conformance.mjs), and regenerate with
    python -m tests.gen_crdt_golden."""
    import hashlib
    from diamond_types_tpu.tools.web_assets import crdt_engine_js
    fx = _golden_fixture()
    cur = hashlib.sha256(crdt_engine_js().encode("utf8")).hexdigest()
    assert cur == fx["js_sha256"], (
        "web_assets.CRDT_HTML engine text drifted from the golden "
        "fixture — see this test's docstring for the regen steps")


def test_conformance_runner_embeds_shipped_js():
    """The node runner must contain the engine source verbatim — it IS
    the executable form of the shipped JS for environments with a JS
    runtime (none exists in this image)."""
    import os
    from diamond_types_tpu.tools.web_assets import crdt_engine_js
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "crdt_conformance.mjs")
    with open(path) as f:
        runner = f.read()
    assert crdt_engine_js() in runner
