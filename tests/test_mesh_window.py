"""Mesh flush windows: ONE shard_map dispatch per flush window.

Covers the PR's tentpole top to bottom:
  * padding contract — `pad_batch_count` shape classes and the
    `lens = -1` sentinel rows surviving the replay kernel untouched;
  * `mesh_fused_replay` byte parity against the per-shard fused path
    and the host oracle on randomized mixed buckets;
  * scheduler-level three-way byte parity (mesh window vs. per-shard
    fused vs. host engine) on identical edit streams;
  * cross-shard poison isolation — a violating doc in shard A's bucket
    cannot corrupt shard B's rows in the shared super-batch;
  * dispatch accounting — `device_calls_per_window == 1.0` with >= 2
    shards' buckets due, vs. one call per bucket on the control;
  * mesh warmup pre-compilation, fencing at window assembly, the prom
    window families, and the --mesh-window CLI flag.

Runs on the CPU-simulated mesh (conftest pins JAX_PLATFORMS=cpu and an
8-device virtual host platform).
"""

import random

import numpy as np
import pytest

from diamond_types_tpu.parallel import mesh as pm
from diamond_types_tpu.serve.metrics import ServeMetrics
from diamond_types_tpu.serve.scheduler import MergeScheduler
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.tpu import flush_fuse as ff

pytestmark = [pytest.mark.mesh, pytest.mark.fused, pytest.mark.serve]

FUSED_OPTS = {"cap": 256, "max_ins": 4}


def _mk_oplog(doc_id: str) -> OpLog:
    ol = OpLog()
    ol.doc_id = doc_id
    return ol


def _random_edits(ol: OpLog, rng: random.Random, n: int,
                  agent: str = "a") -> None:
    a = ol.get_or_create_agent_id(agent)
    for _ in range(n):
        cur = len(ol.checkout_tip().snapshot())
        if cur and rng.random() < 0.3:
            pos = rng.randrange(cur)
            end = min(pos + rng.randint(1, 9), cur)
            ol.add_delete_without_content(a, pos, end)
        else:
            pos = rng.randint(0, cur)
            s = "".join(rng.choice("abcdefgh") for _ in
                        range(rng.randint(1, 11)))
            ol.add_insert(a, pos, s)


def _mk_sched(ols, n_shards, **kw):
    kw.setdefault("engine", "device")
    kw.setdefault("fused", True)
    kw.setdefault("fused_opts", FUSED_OPTS)
    kw.setdefault("flush_docs", 8)
    kw.setdefault("flush_deadline_s", 10.0)
    kw.setdefault("flush_workers", False)
    return MergeScheduler(n_shards, resolve=lambda d: ols[d], **kw)


# ---- padding contract ----------------------------------------------------

def test_pad_batch_count_classes():
    """Divides the mesh, n_devices * pow2 rounding, O(log) classes."""
    assert pm.pad_batch_count(1, 4) == 4
    assert pm.pad_batch_count(4, 4) == 4
    assert pm.pad_batch_count(5, 4) == 8
    assert pm.pad_batch_count(9, 4) == 16
    assert pm.pad_batch_count(3, 2) == 4
    classes = {pm.pad_batch_count(b, 4) for b in range(1, 257)}
    for c in classes:
        assert c % 4 == 0
    # pow2 rounding keeps the jit-cache class count logarithmic
    assert len(classes) <= 8


def test_pad_batch_to_mesh_sentinel_rows_survive_kernel():
    """Padding rows (zero ops + lens=-1 sentinel) must pass through
    the replay kernel unchanged — identifiably inert end to end."""
    import jax.numpy as jnp
    b, n, mi, cap = 3, 2, 2, 16
    pos = np.zeros((b, n), np.int32)
    dlen = np.zeros((b, n), np.int32)
    ilen = np.zeros((b, n), np.int32)
    ilen[:, 0] = 2                      # every real row inserts "xx"
    chars = np.full((b, n, mi), ord("x"), np.int32)
    ppos, pdlen, pilen, pchars, bp = pm.pad_batch_to_mesh(
        pos, dlen, ilen, chars, 4)
    assert bp == 4 and ppos.shape == (4, n)
    docs = jnp.zeros((bp, cap), jnp.int32)
    lens = jnp.full((bp,), -1, jnp.int32).at[:b].set(0)
    run = ff.make_replay_body(mi)
    _out, out_lens = run(docs, lens, jnp.asarray(ppos),
                         jnp.asarray(pdlen), jnp.asarray(pilen),
                         jnp.asarray(pchars))
    got = np.asarray(out_lens)
    assert list(got[:b]) == [2, 2, 2]   # real rows replayed
    assert got[b] == -1                 # sentinel survived


# ---- mesh replay parity --------------------------------------------------

def test_mesh_fused_replay_randomized_parity():
    """Mesh-sharded super-batch replay == per-shard fused replay ==
    host checkout, on randomized mixed buckets re-windowed across
    rounds (committed rows re-enter later super-batches)."""
    rng = random.Random(11)
    mesh = pm.serve_mesh(4)
    ols = [_mk_oplog(f"d{i}") for i in range(6)]
    ols_f = [_mk_oplog(f"d{i}") for i in range(6)]
    rng_f = random.Random(11)
    for i, (ol, olf) in enumerate(zip(ols, ols_f)):
        _random_edits(ol, rng, 2 + i)
        _random_edits(olf, rng_f, 2 + i)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    sess_f = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols_f]
    for rnd in range(3):
        for i, (ol, olf) in enumerate(zip(ols, ols_f)):
            _random_edits(ol, rng, 1 + (i + rnd) % 3)
            _random_edits(olf, rng_f, 1 + (i + rnd) % 3)
            if rnd == 1:
                for o in (ol, olf):
                    b = o.get_or_create_agent_id("b")
                    o.add_insert_at(b, [], 0, "Z" * (i + 1))
        plans = [s.plan_tail() for s in sess]
        ok, _dev, bp, _staged = pm.mesh_fused_replay(mesh, sess, plans)
        assert all(ok)
        assert bp % 4 == 0 and bp >= len(sess)
        ok_f, _ = ff.fused_replay(sess_f,
                                  [s.plan_tail() for s in sess_f])
        assert all(ok_f)
        for s, sf, ol in zip(sess, sess_f, ols):
            assert s.text() == ol.checkout_tip().snapshot()
            assert s.text() == sf.text()


# ---- scheduler-level parity ----------------------------------------------

def test_scheduler_three_way_byte_parity():
    """Identical edit streams through (a) mesh-window scheduler,
    (b) per-shard fused scheduler, (c) host-engine scheduler: every
    doc byte-identical across all three."""
    def mk_logs():
        logs = {}
        for i in range(10):
            ol = _mk_oplog(f"d{i}")
            a = ol.get_or_create_agent_id("seed")
            ol.add_insert(a, 0, f"doc{i}: ")
            logs[f"d{i}"] = ol
        return logs

    logs = [mk_logs() for _ in range(3)]
    scheds = [
        _mk_sched(logs[0], 4, mesh_window=True),
        _mk_sched(logs[1], 4, mesh_window=False),
        _mk_sched(logs[2], 4, engine="host"),
    ]
    assert scheds[0].mesh_window and not scheds[1].mesh_window
    rngs = [random.Random(7) for _ in range(3)]
    for _rnd in range(5):
        for i in range(10):
            d = f"d{i}"
            for lg, r in zip(logs, rngs):
                _random_edits(lg[d], r, 2)
            for s in scheds:
                assert s.submit(d, n_ops=2)["accepted"]
        for s in scheds:
            s.pump(force=True)
    for i in range(10):
        d = f"d{i}"
        texts = [s.text(d) for s in scheds]
        assert texts[0] == texts[1] == texts[2]
        assert texts[0] == logs[0][d].checkout_tip().snapshot()
    m = scheds[0].metrics_json()
    assert m["totals"]["host_fallbacks"] == 0
    assert m["window"]["mesh_docs"] > 0


# ---- cross-shard poison isolation ----------------------------------------

def _docs_on_two_shards(sched, n=2):
    by_shard = {0: [], 1: []}
    i = 0
    while any(len(v) < n for v in by_shard.values()):
        d = f"w{i:03d}"
        s = sched.router.shard_of(d)
        if s in by_shard and len(by_shard[s]) < n:
            by_shard[s].append(d)
        i += 1
        assert i < 4096
    return by_shard


def test_cross_shard_poison_isolation(monkeypatch):
    """A violating doc in shard 0's bucket poisons only ITS row of the
    shared super-batch: shard 1's docs (and shard 0's healthy doc)
    commit device state and stay byte-correct; the violator is evicted
    to the host oracle."""
    ols = {}
    sched = _mk_sched(ols, 2, mesh_window=True)
    by_shard = _docs_on_two_shards(sched)
    docs = by_shard[0] + by_shard[1]
    rng = random.Random(9)
    for d in docs:
        ols[d] = _mk_oplog(d)
        _random_edits(ols[d], rng, 3)
        assert sched.submit(d, n_ops=3)["accepted"]
    sched.pump(force=True)              # builds sessions
    for d in docs:
        _random_edits(ols[d], rng, 2)
        assert sched.submit(d, n_ops=2)["accepted"]

    victim = by_shard[0][0]
    real_plan = ff.FusedDocSession.plan_tail

    def bad_plan(self):
        plan = real_plan(self)
        if self.oplog.doc_id == victim and plan.n_ops:
            plan.dlen[0] = self.max_ins + 1   # device poisons to -1
        return plan

    monkeypatch.setattr(ff.FusedDocSession, "plan_tail", bad_plan)
    sched.pump(force=True)
    monkeypatch.undo()
    m = sched.metrics_json()
    assert m["totals"]["host_fallbacks"] == 1
    assert victim not in sched.banks[0].sessions     # evicted
    for d in by_shard[1]:
        assert d in sched.banks[1].sessions          # untouched shard
    for d in docs:
        assert sched.text(d) == ols[d].checkout_tip().snapshot()


# ---- dispatch accounting -------------------------------------------------

def test_one_dispatch_per_window_vs_per_shard_control():
    """>= 2 shards' buckets due in one window: the mesh path issues
    exactly ONE device program (device_calls_per_window == 1.0); the
    per-shard control pays one dispatch per due bucket."""
    from diamond_types_tpu.obs.devprof import PROFILER

    def run(mesh_window):
        ols = {}
        sched = _mk_sched(ols, 2, mesh_window=mesh_window)
        by_shard = _docs_on_two_shards(sched)
        docs = by_shard[0] + by_shard[1]
        rng = random.Random(3)
        for rnd in range(3):
            for d in docs:
                if rnd == 0:
                    ols[d] = _mk_oplog(d)
                _random_edits(ols[d], rng, 2)
                assert sched.submit(d, n_ops=2)["accepted"]
            sched.pump(force=True)
        for d in docs:
            assert sched.text(d) == ols[d].checkout_tip().snapshot()
        return sched.metrics_json()

    PROFILER.reset()
    PROFILER.enabled = True
    try:
        m = run(mesh_window=True)
        w = m["window"]
        # round 1 builds (no device work); rounds 2-3 each fold BOTH
        # shards' buckets into one dispatch
        assert w["windows"] == 3
        assert w["device_windows"] == 2
        assert w["dispatches"] == 2
        assert w["device_calls_per_window"] == 1.0
        assert w["mesh_docs"] == 8                  # 4 docs x 2 rounds
        assert w["mesh_padded_rows"] >= w["mesh_docs"]
        assert 0 < w["mesh_occupancy"] <= 1
        assert w["shards_hist"] == {"2": 3}
        assert m["fused"]["device_calls"] == 0      # no per-shard rung
        dp = PROFILER.snapshot()
        assert dp["mesh_window"]["dispatches"] == 2
        assert dp["mesh_window"]["docs"] == 8
        assert "mesh" in dp["jit_cache"]
    finally:
        PROFILER.enabled = False
    mc = run(mesh_window=False)
    wc = mc["window"]
    # the control pays one handoff per due bucket: 2 shards -> 2
    assert wc["device_calls_per_window"] == 2.0
    assert wc["mesh_docs"] == 0


# ---- warmup --------------------------------------------------------------

def test_warmup_precompiles_mesh_shape_classes():
    """warmup_fused_cache(mesh_shards=N) compiles every padded-B mesh
    class; a second warmup over the same shapes is all cache hits."""
    from diamond_types_tpu.obs.devprof import PROFILER
    PROFILER.reset()
    PROFILER.enabled = True
    try:
        n = ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                                  shape_classes=(1,), mesh_shards=2)
        # fused batches {1, 2} + mesh padded-B classes {2, 4}
        assert n == 4
        snap1 = PROFILER.snapshot()["jit_cache"]["mesh"]
        assert snap1["misses"] == 2
        ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                              shape_classes=(1,), mesh_shards=2)
        snap2 = PROFILER.snapshot()["jit_cache"]["mesh"]
        assert snap2["hits"] >= snap1["hits"] + 2
        assert snap2["misses"] == snap1["misses"]
    finally:
        PROFILER.enabled = False


def test_scheduler_warmup_covers_first_window():
    """A warmed mesh-window scheduler's first real dispatch must hit
    the mesh jit cache, not compile on the flush path."""
    from diamond_types_tpu.obs.devprof import PROFILER
    ols = {}
    PROFILER.reset()
    PROFILER.enabled = True
    try:
        sched = _mk_sched(ols, 2, mesh_window=True, warmup=True,
                          fused_opts={"cap": 64, "max_ins": 2})
        sched.banks[0].join_warmup()
        misses0 = PROFILER.snapshot()["jit_cache"]["mesh"]["misses"]
        by_shard = _docs_on_two_shards(sched)
        docs = by_shard[0] + by_shard[1]
        rng = random.Random(5)
        for rnd in range(2):
            for d in docs:
                if rnd == 0:
                    ols[d] = _mk_oplog(d)
                _random_edits(ols[d], rng, 1)
                assert sched.submit(d, n_ops=1)["accepted"]
            sched.pump(force=True)
        snap = PROFILER.snapshot()["jit_cache"]["mesh"]
        assert snap["misses"] == misses0     # zero cold compiles
        assert snap["hits"] > 0
    finally:
        PROFILER.enabled = False
    for d in docs:
        assert sched.text(d) == ols[d].checkout_tip().snapshot()


# ---- fencing at window assembly ------------------------------------------

def test_fencing_recheck_at_window_assembly():
    """Work admitted under a lease epoch the host no longer holds is
    dropped when the WINDOW is assembled — it never joins the
    super-batch, and the window records zero dispatches."""
    ols = {}
    sched = _mk_sched(ols, 1, mesh_window=True)
    epoch = {"n": 1}
    sched.epoch_of = lambda d: epoch["n"]
    d = "fenced-doc"
    ols[d] = _mk_oplog(d)
    a = ols[d].get_or_create_agent_id("a")
    ols[d].add_insert(a, 0, "hello")
    assert sched.submit(d, n_ops=1)["accepted"]
    epoch["n"] = 2        # the lease moved between admit and window
    sched.pump(force=True)
    m = sched.metrics_json()
    assert m["totals"]["fenced"] == 1
    assert m["totals"]["syncs"] == 0
    assert m["window"]["windows"] == 1
    assert m["window"]["dispatches"] == 0
    assert m["window"]["device_windows"] == 0
    assert d not in sched.banks[0].sessions


# ---- prom rendering ------------------------------------------------------

def test_prom_renders_window_block():
    from diamond_types_tpu.obs.prom import render_metrics
    m = ServeMetrics(2, 4, 64)
    m.record_window(1, 6, 2, mesh_docs=6, padded_rows=8)
    m.record_window(0, 0, 1)
    text = render_metrics({"serve": m.snapshot()})
    assert "dt_serve_window_windows_total 2" in text
    assert "dt_serve_window_device_windows_total 1" in text
    assert "dt_serve_window_dispatches_total 1" in text
    assert "dt_serve_window_device_calls_per_window 1.0" in text
    assert "dt_serve_window_mesh_docs_total 6" in text
    assert "dt_serve_window_mesh_occupancy 0.75" in text
    assert 'dt_serve_window_shards_total{shards="2"} 1' in text
    lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(lines) == len(set(lines))


# ---- CLI -----------------------------------------------------------------

def test_cli_mesh_window_flag_smoke(capsys):
    """--mesh-window / --no-mesh-window parse; the dry-run report
    carries the window block and the device-calls-per-window figure."""
    from diamond_types_tpu.tools.cli import main
    rc = main(["serve-bench", "--dry-run", "--mesh-window",
               "--no-workers", "--steady-rounds", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "parity OK" in out
    assert "device calls/window" in out


# ---- runtime lock witness ------------------------------------------------

def test_concurrent_windows_witness_acyclic():
    """The runtime lock witness, enabled across concurrent pump and
    read traffic over mesh flush windows, observes an acyclic
    lock-class order graph — no thread was ever seen holding a
    higher-level lock while acquiring a lower one."""
    import threading

    from diamond_types_tpu.analysis import (witness_assert_acyclic,
                                            witness_disable,
                                            witness_enable,
                                            witness_reset,
                                            witness_snapshot)
    witness_reset()
    witness_enable()
    try:
        ols = {}
        sched = _mk_sched(ols, 2, mesh_window=True)
        by_shard = _docs_on_two_shards(sched)
        docs = by_shard[0] + by_shard[1]
        rng = random.Random(17)
        for d in docs:
            ols[d] = _mk_oplog(d)
        for rnd in range(3):
            # edits + submits are single-threaded (raw OpLog appends
            # are not a locked surface); the lock-bearing paths — pump
            # windows and reads — then run concurrently
            for d in docs:
                _random_edits(ols[d], rng, 2)
                assert sched.submit(d, n_ops=2)["accepted"]
            errs = []

            def pumper():
                try:
                    sched.pump(force=True)
                except Exception as e:     # pragma: no cover
                    errs.append(e)

            def reader():
                try:
                    for d in docs:
                        sched.text(d)
                except Exception as e:     # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=pumper) for _ in range(2)]
            threads += [threading.Thread(target=reader) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs
        for d in docs:
            assert sched.text(d) == ols[d].checkout_tip().snapshot()
        snap = witness_snapshot()
        assert snap["enabled"]
        assert snap["acquires"] > 0
        assert snap["edge_count"] > 0
        assert snap["acyclic"], snap
        assert snap["violations"] == []
        witness_assert_acyclic()
    finally:
        witness_disable()
        witness_reset()
