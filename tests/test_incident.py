"""Incident engine tests (obs/incident.py + the serving/runner wiring):
the fake-clock detector matrix (each kind fires exactly once under
cooldown, quiet-from-birth series never alarm), the disabled-path
zero-allocation pin, the evidence-bundle round-trip through a live
server's /debug/incidents endpoints + dt_incident_* prom zero-fill,
and the long-run harness's kill-and-resume contract: a checkpointed
smoke run aborted mid-tape and resumed must converge to the same
deterministic scorecard slice as an uninterrupted control run.
Tier-1 safe: fake clocks, in-process servers on ephemeral ports.
"""

import json
import os
import shutil
import threading
import tracemalloc
import urllib.error
import urllib.request

import pytest

from diamond_types_tpu.obs import Observability
from diamond_types_tpu.obs.incident import (INCIDENT_KINDS,
                                            AnomalyDetector,
                                            IncidentStore)
from diamond_types_tpu.obs.recorder import FlightRecorder
from diamond_types_tpu.obs.timeseries import TimeSeries

pytestmark = pytest.mark.incident


class _Clock:
    """Injectable monotonic clock shared by ring + detector."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _detector(clk, ts=None, recorder=None, store=None, **kw):
    opts = dict(cooldown_s=300.0, rate_window_s=10.0, stall_after_s=30.0,
                warmup_polls=3, spike_factor=8.0, p99_factor=4.0,
                min_rate=0.5, min_p99_s=0.001)
    opts.update(kw)
    ts = ts if ts is not None else TimeSeries(clock=clk)
    return ts, AnomalyDetector(ts, recorder=recorder, store=store,
                               clock=clk, **opts)


# ---- detector matrix (fake clock) ----------------------------------------

def test_rate_stall_fires_exactly_once():
    clk = _Clock()
    ts, det = _detector(clk)
    # warm the series past warmup_polls at a steady 1 op/s
    for _ in range(4):
        for _ in range(10):
            ts.inc("serve.flush")
        assert det.poll() == ()
        clk.t += 10.0
    # go silent past stall_after_s: exactly one rate_stall
    clk.t += 35.0
    fired = det.poll()
    assert [(k, s) for k, s, _ in fired] == [("rate_stall", "serve.flush")]
    assert fired[0][2]["silent_s"] >= 30.0
    # still silent: re-arm requires new flow, not just cooldown
    clk.t += 400.0
    assert det.poll() == ()
    # flow again, then stall again OUTSIDE cooldown: fires anew
    for _ in range(10):
        ts.inc("serve.flush")
    det.poll()
    clk.t += 35.0
    fired = det.poll()
    assert [k for k, _, _ in fired] == ["rate_stall"]


def test_rate_spike_fires_once_then_cooldown_suppresses():
    clk = _Clock()
    ts, det = _detector(clk)
    for _ in range(4):
        for _ in range(5):
            ts.inc("serve.ops")          # steady 0.5 op/s
        assert det.poll() == ()
        clk.t += 10.0
    for _ in range(100):                 # 10 op/s burst: > 8x EWMA
        ts.inc("serve.ops")
    fired = det.poll()
    assert [(k, s) for k, s, _ in fired] == [("rate_spike", "serve.ops")]
    assert fired[0][2]["rate"] > 8.0 * fired[0][2]["ewma"]
    # a second burst inside the cooldown window is deduped, not refired
    before = det.suppressed
    for _ in range(200):
        ts.inc("serve.ops")
    assert det.poll() == ()
    assert det.suppressed == before + 1


def test_p99_step_fires_exactly_once():
    clk = _Clock()
    ts, det = _detector(clk)
    for _ in range(4):
        for _ in range(20):
            ts.observe("serve.flush", 0.010)
        assert det.poll() == ()
        clk.t += 10.0
    for _ in range(20):
        ts.observe("serve.flush", 0.500)   # 50x the trailing p99
    fired = det.poll()
    kinds = [(k, s) for k, s, _ in fired]
    assert ("p99_step", "serve.flush") in kinds
    assert len([k for k, _ in kinds if k == "p99_step"]) == 1
    # same elevated p99 next poll: inside cooldown, suppressed
    for _ in range(20):
        ts.observe("serve.flush", 0.500)
    assert not any(k == "p99_step" for k, _, _ in det.poll())


def test_slo_burn_follows_recorder_transitions():
    clk = _Clock()
    rec = FlightRecorder(capacity=64)
    ts, det = _detector(clk, recorder=rec)
    assert det.poll() == ()
    rec.record("slo_transition", objective="flush_p99",
               series="serve.flush", frm="ok", to="burning",
               fast_burn=20.0, slow_burn=2.0)
    fired = det.poll()
    assert [(k, s) for k, s, _ in fired] == [("slo_burn", "flush_p99")]
    assert fired[0][2]["fast_burn"] == 20.0
    # recovery transitions never alarm; cursor advances past them
    rec.record("slo_transition", objective="flush_p99",
               series="serve.flush", frm="burning", to="ok")
    assert det.poll() == ()
    # re-burn inside the cooldown window: suppressed, not duplicated
    before = det.suppressed
    rec.record("slo_transition", objective="flush_p99",
               series="serve.flush", frm="ok", to="burning")
    assert det.poll() == ()
    assert det.suppressed == before + 1


def test_quiet_from_birth_never_alarms():
    clk = _Clock()
    ts, det = _detector(clk)
    # a series that emits once and dies before warming up: no alarm,
    # ever — the stall watch only arms on established flow
    ts.inc("repl.handoff")
    for _ in range(50):
        assert det.poll() == ()
        clk.t += 60.0
    assert det.snapshot()["watched"] >= 1


def test_detector_opens_bundles_through_store():
    clk = _Clock()
    store = IncidentStore(clock=clk)
    rec = FlightRecorder(capacity=64)
    ts, det = _detector(clk, recorder=rec, store=store)
    rec.record("slo_transition", objective="visibility_p99",
               series="serve.visibility", frm="ok", to="burning")
    det.poll()
    snap = store.snapshot()
    assert snap["total"] == 1 and snap["open"] == 1
    assert snap["by_kind"]["slo_burn"] == 1
    assert store.get(snap["last_id"])["series"] == "visibility_p99"


def test_undeclared_kind_rejected():
    store = IncidentStore()
    with pytest.raises(ValueError):
        store.open_incident("rate_stalled", "x", {})
    assert store.snapshot()["total"] == 0


def test_store_ack_and_capacity_ring():
    clk = _Clock()
    store = IncidentStore(capacity=2, clock=clk)
    ids = [store.open_incident("rate_spike", f"s{i}", {})["id"]
           for i in range(3)]
    snap = store.snapshot()
    assert snap["total"] == 3            # seq survives eviction
    assert store.get(ids[0]) is None     # evicted, ring capacity 2
    assert store.ack(ids[2]) and not store.ack(ids[0])
    assert store.snapshot()["open"] == 1
    idx = store.index_json()
    assert [r["id"] for r in idx["incidents"]] == [ids[2], ids[1]]
    assert idx["incidents"][0]["acknowledged"]


# ---- zero-allocation disabled path ---------------------------------------

def test_disabled_detector_single_branch_zero_alloc():
    """`enabled=False` poll() is ONE branch returning a module-level
    empty tuple: tracemalloc must attribute zero allocations to
    obs/incident.py across 200 polls (mirrors the telemetry pin)."""
    import diamond_types_tpu.obs.incident as inc_mod
    ts = TimeSeries()
    for _ in range(50):
        ts.inc("serve.ops")
    det = AnomalyDetector(ts, enabled=False)

    def _cycle():
        for _ in range(200):
            det.poll()

    _cycle()    # warm interpreter artifacts before measuring
    files = {inc_mod.__file__}
    grew = []
    tracemalloc.start()
    for _attempt in range(3):
        before = tracemalloc.take_snapshot()
        _cycle()
        after = tracemalloc.take_snapshot()
        grew = [st for st in after.compare_to(before, "lineno")
                if st.size_diff > 0
                and st.traceback[0].filename in files
                and st.traceback[0].lineno > 0]
        if not grew:
            break
    tracemalloc.stop()
    assert not grew, [str(g) for g in grew]
    assert det.polls == 0


# ---- bundle round-trip through a live server -----------------------------

def _serve_one(tmp_path=None, **obs_opts):
    from diamond_types_tpu.tools.server import serve
    opts = {"sample_rate": 1.0}
    opts.update(obs_opts)
    httpd = serve(port=0, obs_opts=opts,
                  data_dir=str(tmp_path) if tmp_path else None)
    addr = f"127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, addr


def _get(addr, path):
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.read().decode("utf8")


def test_bundle_round_trip_and_persistence(tmp_path):
    httpd, addr = _serve_one(tmp_path)
    try:
        obs = httpd.store.obs
        # traced traffic first: bundles freeze the last sampled trace
        # ids, and those must resolve via /debug/trace/<id>
        body = json.dumps({"agent": "a1", "version": [], "ops":
                           [{"kind": "ins", "pos": 0,
                             "text": "hello"}]}).encode()
        req = urllib.request.Request(
            f"http://{addr}/doc/d1/edit", data=body,
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5).read()
        deadline = 50
        while not obs.tracer.index(limit=1) and deadline:
            deadline -= 1          # root span ends after the response
            threading.Event().wait(0.01)
        obs.recorder.record("circuit_open", peer="peer-9")
        bundle = obs.incidents.open_incident(
            "rate_stall", "convergence_lag.peer-9", {"silent_s": 31.0})
        idx = json.loads(_get(addr, "/debug/incidents"))
        assert idx["total"] == 1 and idx["open"] == 1
        assert idx["by_kind"]["rate_stall"] == 1
        row = idx["incidents"][0]
        assert row["id"] == bundle["id"]
        got = json.loads(_get(addr, f"/debug/incidents/{bundle['id']}"))
        assert got["kind"] == "rate_stall"
        assert got["series"] == "convergence_lag.peer-9"
        # the frozen recorder tail carries the fault's events
        assert any(ev["kind"] == "circuit_open"
                   for ev in got["recorder_tail"])
        assert {r["name"] for r in got["slo"]} >= {"flush_p99"}
        # the frozen trace ids resolve on the trace debug endpoint
        assert got["traces"], "bundle captured no sampled trace ids"
        trace = json.loads(_get(addr, f"/debug/trace/{got['traces'][0]}"))
        assert trace.get("spans"), trace
        # persisted JSON under the run data dir matches the bundle id
        p = os.path.join(str(tmp_path), "incidents",
                         f"{bundle['id']}.json")
        with open(p, encoding="utf8") as f:
            assert json.load(f)["id"] == bundle["id"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(addr, "/debug/incidents/inc-9999")
        assert ei.value.code == 404
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_prom_families_zero_filled_when_idle():
    httpd, addr = _serve_one()
    try:
        text = _get(addr, "/metrics?format=prom")
        assert "dt_incident_detector_enabled 1" in text
        for kind in INCIDENT_KINDS:
            assert f'dt_incident_opened_total{{kind="{kind}"}} 0' \
                in text
        assert "dt_incident_suppressed_total 0" in text
        assert "dt_incident_open 0" in text
        doc = json.loads(_get(addr, "/metrics"))
        blk = doc["obs"]["incidents"]
        assert blk["total"] == 0 and blk["enabled"]
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_prom_counts_opened_incident():
    httpd, addr = _serve_one()
    try:
        httpd.store.obs.incidents.open_incident("p99_step",
                                                "serve.flush", {})
        text = _get(addr, "/metrics?format=prom")
        assert 'dt_incident_opened_total{kind="p99_step"} 1' in text
        assert "dt_incident_open 1" in text
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---- kill-and-resume determinism -----------------------------------------

def _slice(card):
    """The deterministic scorecard slice: identical between a resumed
    run and an uninterrupted control. Wall-clock metrics are excluded,
    and so is `bytes_received` (and the bytes_per_op derived from it):
    HTTP response bodies carry variable-width float fields, so it
    jitters by a few bytes even between two uninterrupted runs."""
    totals = {k: v for k, v in card["totals"].items()
              if k != "bytes_received"}
    return json.dumps({
        "totals": totals,
        "scenario": card["scenario"],
        "incidents": card.get("incidents"),
        "session_churns": card.get("extra", {}).get("session_churns"),
        "converged": card.get("convergence", {}).get("converged"),
    }, sort_keys=True)


def test_kill_and_resume_byte_identical_scorecard():
    from diamond_types_tpu.workload.runner import run_scenario
    from diamond_types_tpu.workload.spec import get_scenario

    control = run_scenario(get_scenario("smoke"))
    assert control["ok"], control

    part = run_scenario(get_scenario("smoke"), checkpoint_every_s=1.0,
                        stop_after_ticks=3)
    assert part.get("aborted") and part["tick"] == 3
    run_dir = part["resume_dir"]
    try:
        assert os.path.exists(os.path.join(run_dir, "checkpoint.json"))
        card = run_scenario(None, resume_dir=run_dir)
        assert card["ok"] and card["extra"]["resumed"]
        assert card["convergence"]["converged"]
        # the incidents block survives the kill/resume boundary
        assert card["incidents"]["by_kind"] == dict.fromkeys(
            INCIDENT_KINDS, 0) or card["incidents"]["count"] >= 0
        assert _slice(card) == _slice(control)
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
