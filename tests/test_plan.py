"""Plan-based engine vs the M1 engine — the reference's cross-engine
differential strategy (reference: src/listmerge2/test_conversion.rs)."""

import pytest

from diamond_types_tpu.listmerge.plan import compile_plan, merge_via_plan
from tests.test_encode import build_random_oplog


@pytest.mark.parametrize("seed", range(25))
def test_plan_matches_m1_engine(seed):
    ol = build_random_oplog(seed, steps=45)
    m1 = ol.get_xf_operations_full([], ol.version)
    m1_rows = [(lv, op.kind, op.start, op.end, op.fwd, pos)
               for (lv, op, pos) in m1]
    plan_rows, final = merge_via_plan(ol, [], ol.version)
    plan_rows = [(lv, op.kind, op.start, op.end, op.fwd, pos)
                 for (lv, op, pos) in plan_rows]
    assert plan_rows == m1_rows
    assert final == m1.next_frontier
    assert final == ol.version


@pytest.mark.parametrize("seed", range(10))
def test_plan_incremental(seed):
    ol = build_random_oplog(100 + seed, steps=35)
    mid = ol.cg.graph.find_dominators([len(ol) // 2])
    m1 = ol.get_xf_operations_full(mid, ol.version)
    m1_rows = [(lv, pos) for (lv, _op, pos) in m1]
    plan_rows, final = merge_via_plan(ol, mid, ol.version)
    assert [(lv, pos) for (lv, _op, pos) in plan_rows] == m1_rows
    assert final == m1.next_frontier


def test_plan_is_static_schedule():
    """A compiled plan can be executed repeatedly with identical results
    (no hidden state in the schedule)."""
    from diamond_types_tpu.listmerge.plan import execute_plan
    ol = build_random_oplog(7, steps=40)
    plan = compile_plan(ol.cg.graph, [], ol.version)
    assert plan.num_ops() == len(ol)
    r1 = [(lv, pos) for (lv, _o, pos) in
          execute_plan(plan, ol.cg.agent_assignment, ol.ops)]
    r2 = [(lv, pos) for (lv, _o, pos) in
          execute_plan(plan, ol.cg.agent_assignment, ol.ops)]
    assert r1 == r2
