"""Elastic mesh (replicate/rebalance.py + rebalance_soak.py).

Three layers:

  * `PlacementOverrides` in isolation: version monotonicity, the
    LWW merge rule (higher version wins, equal version resolves to
    the lexically smaller target with tombstones smallest), gossip
    payload shape + cap, and the journal round-trip that makes
    placement survive crash-restart;
  * `Rebalancer` against a stub node: the tick only plans under
    stress, picks the least-loaded HEALTHY peer, honors the
    min-load-gap damper and per-doc cooldown, and rolls an aborted
    migration all the way back (override tombstoned, counter bumped);
  * the full `rebalance-soak` acceptance run: flash crowd drives the
    SLO ok -> burning -> ok with at least one live migration, a host
    joined mid-soak absorbs load, the injected abort rolls back, and
    the mesh reconverges with zero split-brain.
"""

import pytest

from diamond_types_tpu.replicate.metrics import ReplicationMetrics
from diamond_types_tpu.replicate.rebalance import (PlacementOverrides,
                                                   Rebalancer)
from diamond_types_tpu.replicate.rebalance_soak import run_rebalance_soak

pytestmark = pytest.mark.elastic


# ---- PlacementOverrides ---------------------------------------------------

def test_override_set_clear_versions_are_monotonic():
    t = PlacementOverrides()
    assert t.target_of("d0") is None
    assert t.version_of("d0") == 0
    assert t.set("d0", "hostB") == 1
    assert t.target_of("d0") == "hostB"
    assert t.set("d0", "hostC") == 2
    assert t.target_of("d0") == "hostC"
    assert t.size() == 1
    # clear is a tombstone at a BUMPED version, not a delete
    assert t.clear("d0") == 3
    assert t.target_of("d0") is None
    assert t.version_of("d0") == 3
    assert t.size() == 0
    assert t.as_json() == {"d0": {"target": None, "ver": 3}}


def test_merge_precedence_higher_version_wins():
    t = PlacementOverrides()
    t.set("d0", "hostB")                        # ver 1
    assert t.merge([["d0", "hostC", 5]]) == 1   # newer wins
    assert t.target_of("d0") == "hostC"
    assert t.version_of("d0") == 5
    assert t.merge([["d0", "hostZ", 3]]) == 0   # stale ignored
    assert t.target_of("d0") == "hostC"
    # a newer tombstone retracts a set entry
    assert t.merge([["d0", None, 6]]) == 1
    assert t.target_of("d0") is None
    assert t.version_of("d0") == 6


def test_merge_equal_version_resolves_to_smaller_target():
    """Equal versions must converge without coordination: lexically
    smaller target wins, and a tombstone sorts below every target —
    any fold order reaches the same table."""
    t = PlacementOverrides()
    t.merge([["d0", "hostB", 2]])
    assert t.merge([["d0", "hostC", 2]]) == 0   # larger target loses
    assert t.target_of("d0") == "hostB"
    assert t.merge([["d0", "hostA", 2]]) == 1   # smaller target wins
    assert t.target_of("d0") == "hostA"
    assert t.merge([["d0", None, 2]]) == 1      # tombstone is smallest
    assert t.target_of("d0") is None
    assert t.merge([["d0", "hostA", 2]]) == 0   # ...and sticks
    # fold the same three entries in the opposite order on a second
    # table: both converge to the tombstone at ver 2
    u = PlacementOverrides()
    u.merge([["d0", None, 2]])
    u.merge([["d0", "hostA", 2]])
    u.merge([["d0", "hostC", 2]])
    assert u.as_json() == t.as_json()


def test_merge_rejects_malformed_rows():
    t = PlacementOverrides()
    assert t.merge("not-a-list") == 0
    assert t.merge([["d0", "hostB"],            # wrong arity
                    ["d1", "hostB", "notint"],  # bad version type
                    [7, "hostB", 1],            # bad doc type
                    ["d2", 9, 1],               # bad target type
                    ["d3", "hostB", 1]]) == 1   # the one valid row
    assert t.as_json() == {"d3": {"target": "hostB", "ver": 1}}


def test_gossip_payload_roundtrips_and_caps():
    t = PlacementOverrides()
    for i in range(8):
        t.set(f"d{i}", "hostB")
    t.clear("d3")
    payload = t.gossip_payload()
    # tombstones ride the payload like sets so clears propagate
    assert ["d3", None, 2] in payload
    fresh = PlacementOverrides()
    assert fresh.merge(payload) == 8
    assert fresh.as_json() == t.as_json()
    assert len(t.gossip_payload(cap=3)) == 3


class _JournalStub:
    def __init__(self):
        self.rows = {}

    def note_override(self, doc, target, ver):
        self.rows[doc] = {"target": target, "ver": ver}

    def restored_overrides(self):
        return dict(self.rows)


def test_overrides_journal_roundtrip_including_tombstones():
    j = _JournalStub()
    t = PlacementOverrides(journal=j)
    t.set("d0", "hostB")
    t.set("d1", "hostC")
    t.clear("d1")
    # merged-in entries are journaled too: EVERY host's placement must
    # survive a crash, not just the migration initiator's
    t.merge([["d2", "hostB", 4]])
    restored = PlacementOverrides(journal=j)
    assert restored.as_json() == t.as_json()
    assert restored.target_of("d0") == "hostB"
    assert restored.target_of("d1") is None
    assert restored.version_of("d1") == 2
    assert restored.version_of("d2") == 4


def test_overrides_bump_rebalance_metrics():
    m = ReplicationMetrics("hostA")
    t = PlacementOverrides(metrics=m)
    t.set("d0", "hostB")
    t.clear("d0")
    t.merge([["d1", "hostC", 3]])
    assert m.get("rebalance", "overrides_set") == 1
    assert m.get("rebalance", "overrides_cleared") == 1
    assert m.get("rebalance", "override_merges") == 1


# ---- Rebalancer against a stub node ---------------------------------------

class _Leases:
    def __init__(self, held):
        self.held = list(held)

    def held_ids(self):
        return list(self.held)

    def held_count(self):
        return len(self.held)


class _Membership:
    def __init__(self, members):
        self.members = list(members)

    def universe(self):
        return list(self.members)


class _Table:
    def __init__(self, down=()):
        self.down = set(down)

    def is_healthy(self, m):
        return m not in self.down


class _Slo:
    def __init__(self, state):
        self.state = state

    def evaluate(self):
        return [{"name": "soak_edit_rtt", "state": self.state}]


class _Obs:
    def __init__(self, state="ok"):
        self.slo = _Slo(state)


class _Node:
    """Just enough ReplicaNode surface for Rebalancer: leases,
    membership view, gossiped peer loads, overrides, metrics and an
    instrumented handoff whose outcome the test controls."""

    def __init__(self, held=("d1", "d2", "d3"),
                 peers=("hostB", "hostC"), down=(),
                 peer_load=None, handoff_ok=True):
        self.self_id = "hostA"
        self.leases = _Leases(held)
        self.membership = _Membership([self.self_id, *peers])
        self.table = _Table(down)
        self.peer_load = dict(peer_load or {})
        self.metrics = ReplicationMetrics(self.self_id)
        self.overrides = PlacementOverrides(metrics=self.metrics)
        self.obs = None
        self.rejoining = False
        self.store = object()           # no scheduler: parking no-ops
        self.handoff_ok = handoff_ok
        self.handoffs = []
        self._now = 100.0

    def clock(self):
        return self._now

    def handoff(self, doc_id, target, override_version=None):
        self.handoffs.append((doc_id, target, override_version))
        return self.handoff_ok


def test_tick_is_a_noop_when_healthy_or_disabled():
    n = _Node()
    rb = Rebalancer(n, obs=_Obs("ok"))
    assert rb.tick() == {"stressed": [], "migrated": [], "aborted": [],
                         "promoted": [], "demoted": []}
    assert n.handoffs == []
    # stressed but disabled / rejoining: still a no-op
    rb2 = Rebalancer(n, obs=_Obs("burning"), enabled=False)
    assert rb2.tick()["migrated"] == []
    n.rejoining = True
    rb3 = Rebalancer(n, obs=_Obs("burning"))
    assert rb3.tick()["migrated"] == []
    assert n.handoffs == []


def test_act_on_narrows_the_trigger_states():
    # a conservative deployment acts only on burning: warnings are
    # not stress, burning still is
    n = _Node(peer_load={"hostB": 0, "hostC": 1})
    rb = Rebalancer(n, obs=_Obs("warning"), act_on=("burning",))
    assert rb.tick() == {"stressed": [], "migrated": [], "aborted": [],
                         "promoted": [], "demoted": []}
    rb2 = Rebalancer(n, obs=_Obs("burning"), act_on=("burning",))
    assert rb2.tick()["migrated"] == [["d1", "hostB"]]


def test_stressed_tick_migrates_offender_to_least_loaded_peer():
    n = _Node(peer_load={"hostB": 0, "hostC": 1})
    rb = Rebalancer(n, obs=_Obs("burning"))
    out = rb.tick()
    assert out["stressed"] == ["soak_edit_rtt"]
    # one migration per tick, lexically-first doc (cold sketch), to the
    # least-loaded peer; the override version rides the handoff
    assert out["migrated"] == [["d1", "hostB"]]
    assert n.handoffs == [("d1", "hostB", 1)]
    assert n.overrides.target_of("d1") == "hostB"
    assert n.metrics.get("rebalance", "migrations_started") == 1
    assert n.metrics.get("rebalance", "migrations_completed") == 1


def test_unhealthy_peer_is_never_a_target():
    n = _Node(peer_load={"hostB": 0, "hostC": 1}, down=("hostB",))
    rb = Rebalancer(n, obs=_Obs("warning"))
    assert rb.tick()["migrated"] == [["d1", "hostC"]]


def test_min_load_gap_dampens_ping_pong():
    # every peer within the gap of our own load: stressed but nowhere
    # worth shedding to — plan must stay empty
    n = _Node(held=("d1", "d2"), peer_load={"hostB": 2, "hostC": 2})
    rb = Rebalancer(n, obs=_Obs("burning"), min_load_gap=1)
    out = rb.tick()
    assert out["stressed"] and out["migrated"] == []
    assert n.handoffs == []


def test_cooldown_blocks_immediate_retry_of_same_doc():
    n = _Node(held=("d1",), peer_load={"hostB": 0, "hostC": 5})
    rb = Rebalancer(n, obs=_Obs("burning"), cooldown_s=3.0)
    assert rb.tick()["migrated"] == [["d1", "hostB"]]
    assert rb.tick()["migrated"] == []       # same instant: cooling
    n._now += 5.0
    assert rb.tick()["migrated"] == [["d1", "hostB"]]
    assert len(n.handoffs) == 2


def test_aborted_migration_rolls_back_override():
    n = _Node(held=("d1",), peer_load={"hostB": 0, "hostC": 5},
              handoff_ok=False)
    rb = Rebalancer(n, obs=_Obs("burning"))
    out = rb.tick()
    assert out["aborted"] == [["d1", "hostB"]]
    assert out["migrated"] == []
    # override tombstoned (set at ver 1, cleared at ver 2): routing
    # stays at the source and the clear gossips over the stale set
    assert n.overrides.target_of("d1") is None
    assert n.overrides.version_of("d1") == 2
    assert n.metrics.get("rebalance", "migrations_started") == 1
    assert n.metrics.get("rebalance", "migrations_completed") == 0
    assert n.metrics.get("rebalance", "migrations_aborted") == 1


# ---- the soak: flash crowd end-to-end --------------------------------------

def test_flash_crowd_soak_migrates_joins_and_recovers():
    """One full rebalance-soak run (the CLI acceptance gate) asserted
    field by field: the flash crowd burns the SLO, the rebalancer
    sheds the hot doc, the mid-soak joiner absorbs load, the injected
    abort rolls back cleanly, and the mesh reconverges byte-identical
    with zero split-brain."""
    rep = run_rebalance_soak()
    assert rep["ok"], rep
    assert rep["settled"]
    # the SLO journey: healthy -> burning under the crowd -> back to ok
    assert rep["slo_states"][0] == "ok"
    assert rep["burning_seen"]
    assert rep["slo_states"][-1] == "ok"
    assert rep["slo_journey_ok"]
    # at least one live migration moved the hot doc off the burning host
    assert len(rep["migrations"]) >= 1
    # scale-out: the host joined at first stress ended up holding load
    assert rep["joined"]
    assert rep["join_absorbed"]
    # the abort injection rolled back: holder unchanged, override
    # tombstoned, migrations_aborted bumped
    assert rep["abort_rollback_ok"]
    assert rep["converged"]
    assert rep["zero_split_brain"]
