"""Protocol model checker (analysis/explore/): the tier-1 smoke.

Small-depth but EXHAUSTIVE runs of the explorer over the real
lease/quorum/fencing tree:

  * every scenario explores clean and complete at a depth that
    finishes in seconds — the "zero violations on the real code"
    half of the adequacy argument;
  * every seeded protocol mutation is caught at its published depth
    with a minimized, replayable witness trace — the "the invariants
    actually bite" half;
  * one minimized trace is replayed end-to-end: it reproduces the
    violation with the mutation applied and passes clean without it;
  * exploration is deterministic (same report twice), the
    sleep-set/dedup machinery demonstrably prunes, and the
    max-states valve reports truncation honestly;
  * the `dt-explore` CLI gate: exit 0 on the clean tree, `--mutate`
    exits 0 only when 7/7 mutations are detected;
  * the verdict reaches obs: snapshot()['explore'] + dt_explore_*
    prom families.
"""

import json
import os
import subprocess
import sys

import pytest

from diamond_types_tpu.analysis.explore import (ALL_INVARIANTS,
                                                MUTATIONS, SCENARIOS,
                                                explore, replay_trace)

pytestmark = pytest.mark.analysis

# depth per scenario chosen so the full run is exhaustive (complete=
# True) yet finishes in a few seconds on one CPU; handoff has the
# widest action set so it gets the shallowest bound
SMOKE_DEPTHS = {"handoff": 3, "crash-recovery": 4,
                "renewal": 5, "tiebreak": 4, "migration": 3,
                "writer-group": 3}


# ---- the real tree is clean ----------------------------------------------

@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_scenario_explores_clean_and_complete(scenario):
    rep = explore(scenario, depth=SMOKE_DEPTHS[scenario])
    assert rep["ok"], rep["violations"]
    assert rep["complete"]
    assert not rep["truncated"]
    assert rep["states"] > 1
    # every executed edge lands in a (possibly already-seen) state
    assert rep["transitions"] == rep["states"] - 1


def test_reduction_machinery_prunes():
    """Dedup and sleep sets must actually fire on a scenario with
    commuting actions — otherwise the POR is dead code and deeper
    bounds silently cost full factorial blowup."""
    rep = explore("handoff", depth=3)
    assert rep["dedup_hits"] > 0
    assert rep["sleep_skips"] > 0


def test_exploration_is_deterministic():
    a = explore("renewal", depth=4)
    b = explore("renewal", depth=4)
    for k in ("states", "transitions", "dedup_hits", "sleep_skips",
              "violations", "ok", "complete"):
        assert a[k] == b[k], k


def test_max_states_valve_reports_truncation():
    rep = explore("handoff", depth=3, max_states=10)
    assert rep["truncated"]
    assert not rep["complete"]
    assert rep["ok"]            # truncated-but-clean is still ok
    assert rep["states"] <= 11


def test_unknown_invariant_rejected():
    with pytest.raises(ValueError):
        explore("renewal", depth=2, invariants=("no-such-invariant",))
    assert "convergence" in ALL_INVARIANTS


# ---- mutation adequacy ---------------------------------------------------

@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_mutation_is_detected_with_minimized_trace(name):
    m = MUTATIONS[name]
    rep = explore(m.scenario, depth=m.depth, mutation=m)
    assert not rep["ok"], f"{name}: explorer missed the mutation"
    v = rep["violations"][0]
    assert v["invariant"] in m.expect, v
    assert len(v["minimized_trace"]) >= 1
    assert len(v["minimized_trace"]) <= len(v["trace"])


def test_minimized_trace_replays_end_to_end():
    """The emitted witness is replayable verbatim: with the mutation
    applied it reproduces the same invariant violation from a fresh
    world; without the mutation the identical schedule passes clean
    (the bug lives in the mutation, not the schedule)."""
    m = MUTATIONS["promise-persist-skip"]
    rep = explore(m.scenario, depth=m.depth, mutation=m)
    v = rep["violations"][0]
    doc = {"scenario": m.scenario, "invariants": rep["invariants"],
           "invariant": v["invariant"],
           "minimized_trace": v["minimized_trace"]}
    with_mut = replay_trace(doc, mutation=m)
    assert with_mut["ok"], with_mut
    assert with_mut["invariant"] == v["invariant"]
    clean = replay_trace(doc)
    assert not clean["ok"]
    assert not clean["violation"], clean


def test_malformed_trace_is_rejected_not_applied():
    """A hand-edited trace with an impossible step (restart of a live
    node) must be rejected by the enabledness guard, not applied."""
    doc = {"scenario": "crash-recovery", "invariant": None,
           "minimized_trace": [
               {"op": "restart", "node": "n2"}]}
    out = replay_trace(doc)
    assert not out["violation"]


# ---- CLI gate ------------------------------------------------------------

def _cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "diamond_types_tpu.tools.cli",
         "dt-explore", *argv],
        capture_output=True, text=True, env=env)


def test_cli_clean_scenario_exits_zero():
    out = _cli("--scenario", "renewal", "--depth", "4", "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"] and doc["complete"]
    assert doc["scenario"] == "renewal"


def test_cli_mutate_gate_detects_all():
    out = _cli("--mutate", "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"]
    assert doc["detected"] == doc["total"] == len(MUTATIONS)
    for r in doc["results"]:
        assert r["detected"], r
        assert r["invariant"] in r["expect"]
        assert r["minimized_trace"]


def test_cli_unknown_scenario_exits_two():
    out = _cli("--scenario", "nope", "--depth", "2")
    assert out.returncode == 2
    assert "unknown scenario" in out.stderr


# ---- obs wiring ----------------------------------------------------------

def test_explore_verdict_reaches_obs_and_prom():
    from diamond_types_tpu.analysis.explore import publish_report
    from diamond_types_tpu.obs import Observability
    from diamond_types_tpu.obs.prom import render_metrics
    rep = explore("renewal", depth=3)
    publish_report(rep)
    obs = Observability(enabled=False)
    snap = obs.snapshot()
    assert snap["explore"]["scenario"] == "renewal"
    assert snap["explore"]["ok"]
    text = render_metrics({"obs": snap})
    assert 'dt_explore_ok{scenario="renewal"} 1' in text
    assert "dt_explore_states_total" in text
    assert 'dt_explore_complete{scenario="renewal"} 1' in text
