"""Concurrency invariant analyzer (analysis/): dt-lint + lock witness.

Covers the static_analysis PR top to bottom:
  * each lint rule fires on its seeded known-bad fixture
    (tests/fixtures/analysis/) and names the right line;
  * same-line `# dt-lint: ignore[rule]` and `# dt-lint: skip-file`
    suppressions silence findings;
  * the repaired tree lints CLEAN — `cli dt-lint --fail-on warn`
    exits 0 (the tier-1 gate) and nonzero when pointed at a fixture;
  * the runtime lock witness: order-graph edges, cycle detection,
    same-class rank monotonicity, disabled no-op, reentrancy;
  * regression pins for the two tree repairs this PR shipped — the
    sorted `_flush_window` device-lock acquisition and the
    admit-gated read path that no longer dispatches under the oplog
    guard.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from diamond_types_tpu.analysis import (make_lock, run_lint,
                                        witness_assert_acyclic,
                                        witness_disable, witness_enable,
                                        witness_reset, witness_snapshot)
from diamond_types_tpu.analysis.lint import (SEVERITY, render_human,
                                             render_json)

pytestmark = pytest.mark.analysis

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "analysis")


def _lint_fixture(name):
    return run_lint(paths=[os.path.join(FIXTURES, name)])


@pytest.fixture(autouse=True)
def _witness_clean():
    witness_reset()
    yield
    witness_disable()
    witness_reset()


# ---- rules on seeded fixtures --------------------------------------------

@pytest.mark.parametrize("fixture,rule,line,severity", [
    ("bad_lock_order.py", "lock-order", 12, "error"),
    ("bad_hydration_lock_order.py", "lock-order", 14, "error"),
    ("bad_read_lock_order.py", "lock-order", 15, "error"),
    ("bad_rebalance_lock_order.py", "lock-order", 14, "error"),
    ("bad_writergroup_lock_order.py", "lock-order", 15, "error"),
    ("bad_qos_lock_order.py", "lock-order", 17, "error"),
    ("bad_ts_lock_order.py", "lock-order", 15, "error"),
    ("bad_incident_lock_order.py", "lock-order", 15, "error"),
    ("bad_wire_lock_order.py", "lock-order", 14, "error"),
    ("bad_xform_lock_order.py", "lock-order", 15, "error"),
    ("bad_steer_lock_order.py", "lock-order", 15, "error"),
    ("bad_unsorted_locks.py", "unsorted-locks", 15, "error"),
    ("bad_device_under_lock.py", "device-under-lock", 13, "error"),
    ("bad_unfenced_mutation.py", "unfenced-mutation", 15, "error"),
    ("bad_jit_impurity.py", "jit-impurity", 14, "warn"),
    ("bad_jit_cache_key.py", "jit-cache-key", 13, "warn"),
    ("bad_blocking_call.py", "blocking-call-under-lock", 14, "warn"),
    ("bad_unguarded_acquire.py", "unguarded-acquire", 12, "error"),
    ("bad_metrics_drift.py", "metrics-schema-drift", 11, "error"),
    ("bad_qos_metrics_drift.py", "metrics-schema-drift", 12, "error"),
    ("bad_incident_metrics_drift.py", "metrics-schema-drift", 13, "error"),
    ("bad_exemplar_drift.py", "metrics-schema-drift", 9, "error"),
    ("bad_stale_suppression.py", "stale-suppression", 11, "warn"),
    # the two historical bugs PR 7's tree repairs fixed, re-expressed
    # as seeded fixtures so the rules that caught them stay honest
    ("bad_unsorted_flush_window.py", "unsorted-locks", 18, "error"),
    ("bad_read_under_oplog.py", "device-under-lock", 16, "error"),
])
def test_rule_fires_on_seeded_fixture(fixture, rule, line, severity):
    report = _lint_fixture(fixture)
    assert not report["ok"]
    assert report["by_rule"][rule] >= 1, render_human(report)
    v = next(v for v in report["violations"] if v["rule"] == rule)
    assert v["line"] == line
    assert v["severity"] == severity
    assert v["path"].endswith(fixture)
    # no cross-talk: the fixture seeds exactly one rule
    assert {v["rule"] for v in report["violations"]} == {rule}


def test_severity_split_counts():
    report = run_lint(paths=[FIXTURES])
    assert report["errors"] == sum(
        1 for v in report["violations"] if v["severity"] == "error")
    assert report["warnings"] == len(report["violations"]) \
        - report["errors"]
    assert report["errors"] >= 4 and report["warnings"] >= 3
    doc = json.loads(render_json(report))
    assert doc["by_rule"] == report["by_rule"]


def test_same_line_suppression_silences():
    report = _lint_fixture("suppressed_ok.py")
    assert report["ok"], render_human(report)


def test_skip_file_suppression_silences():
    report = _lint_fixture("skipped_file.py")
    assert report["ok"], render_human(report)


def test_disable_flag_drops_rule():
    report = run_lint(paths=[os.path.join(FIXTURES, "bad_lock_order.py")],
                      disable=["lock-order"])
    assert report["ok"]


# ---- the tree itself lints clean -----------------------------------------

def test_clean_tree_lints_zero():
    """The repaired tree is the fixture for 'exit 0': every rule runs
    over serve/, replicate/, tpu/, parallel/, tools/ and finds
    nothing."""
    report = run_lint()
    assert report["files"] >= 30
    assert report["ok"], render_human(report)
    assert set(report["by_rule"]) == set(SEVERITY)


def test_cli_dt_lint_gate():
    """Tier-1 gate: `cli dt-lint --fail-on warn` exits 0 on the tree,
    nonzero when a seeded fixture is in scope."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base = [sys.executable, "-m", "diamond_types_tpu.tools.cli",
            "dt-lint", "--fail-on", "warn"]
    clean = subprocess.run(base, capture_output=True, text=True,
                           env=env)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 errors, 0 warnings" in clean.stdout
    for name in sorted(os.listdir(FIXTURES)):
        if not name.startswith("bad_"):
            continue
        bad = subprocess.run(
            base + ["--json", os.path.join(FIXTURES, name)],
            capture_output=True, text=True, env=env)
        assert bad.returncode == 1, name
        doc = json.loads(bad.stdout)
        assert sum(doc["by_rule"].values()) >= 1, name


def test_cli_fail_on_error_ignores_warnings():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    warn_only = subprocess.run(
        [sys.executable, "-m", "diamond_types_tpu.tools.cli",
         "dt-lint", "--fail-on", "error",
         os.path.join(FIXTURES, "bad_jit_impurity.py")],
        capture_output=True, text=True, env=env)
    assert warn_only.returncode == 0, warn_only.stdout


# ---- runtime lock witness ------------------------------------------------

def test_witness_records_order_edges():
    witness_enable()
    g = make_lock("w.global", "global")
    s = make_lock("w.shard", "shard")
    with g:
        with s:
            pass
    snap = witness_snapshot()
    assert snap["edges"] == {"global->shard": 1}
    assert snap["acquires"] == 2
    assert snap["acyclic"]
    witness_assert_acyclic()


def test_witness_detects_cycle():
    witness_enable()
    g = make_lock("w.global", "global")
    s = make_lock("w.shard", "shard")
    with g:
        with s:
            pass
    with s:
        with g:     # backwards: closes the global<->shard cycle
            pass
    snap = witness_snapshot()
    assert not snap["acyclic"]
    assert any("global" in c and "shard" in c for c in snap["cycles"])
    with pytest.raises(AssertionError):
        witness_assert_acyclic()


def test_witness_same_class_rank_monotonicity():
    witness_enable()
    a = make_lock("shard[0]", "shard", rank=0)
    b = make_lock("shard[1]", "shard", rank=1)
    with a:
        with b:     # ascending rank: fine
            pass
    assert witness_snapshot()["violation_count"] == 0
    with b:
        with a:     # descending rank within one class: flagged
            pass
    snap = witness_snapshot()
    assert snap["violation_count"] == 1
    assert snap["violations"][0]["kind"] == "unsorted-same-class"
    with pytest.raises(AssertionError):
        witness_assert_acyclic()


def test_witness_disabled_is_noop():
    lk = make_lock("w.off", "global")
    inner = make_lock("w.off2", "shard")
    with lk:
        with inner:
            pass
    snap = witness_snapshot()
    assert not snap["enabled"]
    assert snap["acquires"] == 0
    assert snap["edge_count"] == 0
    assert snap["acyclic"]


def test_witness_reentrant_and_threaded():
    witness_enable()
    r = make_lock("w.re", "repl.leases", reentrant=True)
    leaf = make_lock("w.leaf", "leaf")
    with r:
        with r:                 # same-object re-acquire: no edge
            with leaf:
                pass
    snap = witness_snapshot()
    assert snap["edges"] == {"repl.leases->leaf": 1}

    def worker():
        with r:
            with leaf:
                pass
    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    witness_assert_acyclic()


# ---- regression pins for this PR's tree repairs --------------------------

def _tree_report(*parts):
    from diamond_types_tpu.analysis.lint import repo_root
    return run_lint(paths=[os.path.join(repo_root(), *parts)])


def test_flush_window_device_locks_stay_sorted():
    """Regression: scheduler._flush_window acquires its device locks
    via the sorted-shards comprehension; reintroducing an unsorted
    acquisition loop (or a dispatch under the global lock) trips the
    lint again."""
    report = _tree_report("serve", "scheduler.py")
    assert report["by_rule"]["unsorted-locks"] == 0, render_human(report)
    assert report["by_rule"]["lock-order"] == 0
    assert report["by_rule"]["device-under-lock"] == 0


def test_read_path_stays_fenced_and_lock_clean():
    """Regression: scheduler.text serves unadmitted docs from the
    durable oplog tip (admit gate) and bank.text splits the oplog read
    from the device fetch — neither dispatches under the oplog
    guard."""
    for parts in (("serve", "scheduler.py"), ("serve", "bank.py")):
        report = _tree_report(*parts)
        assert report["by_rule"]["device-under-lock"] == 0, \
            render_human(report)
        assert report["by_rule"]["unfenced-mutation"] == 0


def test_text_unadmitted_doc_serves_oplog_tip():
    """Behavioral half of the admit-gate repair: a doc the ownership
    gate rejects is still readable — served from the durable oplog
    tip, with no device session ever built for it."""
    from diamond_types_tpu.serve.scheduler import MergeScheduler
    from diamond_types_tpu.text.oplog import OpLog
    ol = OpLog()
    ol.doc_id = "d0"
    a = ol.get_or_create_agent_id("a")
    ol.add_insert(a, 0, "hello")
    sched = MergeScheduler(1, resolve=lambda d: ol, engine="host",
                           flush_workers=False,
                           admit=lambda d: False)
    assert sched.text("d0") == "hello"
    assert sched.banks[0].sessions.get("d0") is None
