"""Real-world editing-trace replay (reference: crates/bench/src/main.rs:17-72;
SURVEY.md §4.4). The smaller traces run in CI; the big ones are exercised by
bench.py.
"""

import os

import pytest

from diamond_types_tpu.text.trace import load_trace, replay_direct, replay_into_oplog
from tests.conftest import reference_path

BENCH = reference_path("benchmark_data")


def trace_path(name):
    p = os.path.join(BENCH, name)
    if not os.path.exists(p):
        pytest.skip(f"missing {p}")
    return p


@pytest.mark.parametrize("name", ["sveltecomponent.json.gz", "seph-blog1.json.gz"])
def test_linear_trace_replay(name):
    data = load_trace(trace_path(name))
    assert replay_direct(data) == data.end_content

    ol = replay_into_oplog(data)
    assert len(ol) == data.num_ops() or len(ol) > 0
    b = ol.checkout_tip()
    assert b.snapshot() == data.end_content


def test_friendsforever_flat():
    data = load_trace(trace_path("friendsforever_flat.json.gz"))
    ol = replay_into_oplog(data)
    b = ol.checkout_tip()
    assert b.snapshot() == data.end_content
