"""Real-world editing-trace replay (reference: crates/bench/src/main.rs:17-72;
SURVEY.md §4.4). The smaller traces run in CI; the big ones are exercised by
bench.py.
"""

import os

import pytest

from diamond_types_tpu.text.trace import (load_trace, replay_direct,
                                          replay_into_oplog,
                                          replay_into_oplog_grouped)
from tests.conftest import reference_path

BENCH = reference_path("benchmark_data")


def trace_path(name):
    p = os.path.join(BENCH, name)
    if not os.path.exists(p):
        pytest.skip(f"missing {p}")
    return p


@pytest.mark.parametrize("name", ["sveltecomponent.json.gz", "seph-blog1.json.gz"])
def test_linear_trace_replay(name):
    data = load_trace(trace_path(name))
    assert replay_direct(data) == data.end_content

    ol = replay_into_oplog(data)
    assert len(ol) == data.num_ops() or len(ol) > 0
    b = ol.checkout_tip()
    assert b.snapshot() == data.end_content


def test_friendsforever_flat():
    data = load_trace(trace_path("friendsforever_flat.json.gz"))
    ol = replay_into_oplog(data)
    b = ol.checkout_tip()
    assert b.snapshot() == data.end_content


@pytest.mark.parametrize("name", ["sveltecomponent.json.gz",
                                  "friendsforever_flat.json.gz"])
def test_grouped_replay_equivalent(name):
    """Bulk ingest (apply_local_patches) is semantically identical to the
    per-op append path: same LV count, same agent mapping, same text."""
    data = load_trace(trace_path(name))
    a = replay_into_oplog(data)
    b = replay_into_oplog_grouped(data)
    assert len(a) == len(b) == data.num_ops() or len(a) == len(b)
    assert b.checkout_tip().snapshot() == data.end_content
    assert (a.cg.local_to_remote_frontier(a.version)
            == b.cg.local_to_remote_frontier(b.version))


def test_grouped_replay_fuzz_patches():
    """Random patch streams (incl. backspace runs, direction flips, mixed
    ins+del patches): grouped == per-op, run-for-run encodable."""
    import random
    from diamond_types_tpu.text.oplog import OpLog
    from diamond_types_tpu.encoding.encode import encode_oplog
    from diamond_types_tpu.encoding.decode import load_oplog

    for seed in range(12):
        rng = random.Random(seed)
        doc_len = 0
        patches = []
        for _ in range(rng.randrange(1, 60)):
            nd = ins = 0
            text = ""
            if doc_len > 2 and rng.random() < 0.45:
                p = rng.randrange(0, doc_len - 1)
                nd = rng.randrange(1, min(4, doc_len - p) + 1)
            else:
                p = rng.randrange(0, doc_len + 1)
                ins = rng.randrange(1, 5)
                text = "".join(rng.choice("abXY") for _ in range(ins))
            patches.append((p, nd, text))
            doc_len += ins - nd
        a = OpLog()
        ag = a.get_or_create_agent_id("t")
        for (p, nd, text) in patches:
            if nd:
                a.add_delete_without_content(ag, p, p + nd)
            if text:
                a.add_insert(ag, p, text)
        b = OpLog()
        bg = b.get_or_create_agent_id("t")
        b.apply_local_patches(bg, patches)
        assert len(a) == len(b), seed
        assert a.checkout_tip().snapshot() == b.checkout_tip().snapshot(), seed
        # round-trips through the wire format identically
        dec = load_oplog(encode_oplog(b))
        assert dec.checkout_tip().snapshot() == a.checkout_tip().snapshot()
