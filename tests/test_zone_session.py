"""Device-resident incremental sessions (tpu/zone_session.py) — the
merge-per-edit realtime pattern, parity-fuzzed against the tracker
engine after every sync (reference hot path: src/list/merge.rs:63-96).
"""

import random

import pytest

from conftest import reference_path
from diamond_types_tpu import OpLog
from diamond_types_tpu.tpu.zone_session import DeviceZoneSession

from test_zone import random_edit


@pytest.mark.parametrize("seed", range(10))
def test_session_realtime_fuzz(seed):
    """2-3 peers edit from their own heads; the session folds each batch
    incrementally and must match a fresh checkout every time."""
    rng = random.Random(8800 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("ann", "bo", "cy")]
    heads = {a: ([], "") for a in agents}
    # seed history so the session starts non-trivially
    v, c = heads[agents[0]]
    for _ in range(5):
        v, c = random_edit(rng, ol, agents[0], v, c)
    for a in agents:
        heads[a] = (v, c)
    sess = DeviceZoneSession(ol, max_chars=32)
    assert sess.text() == ol.checkout_tip().snapshot()

    for step in range(30):
        a = agents[rng.randrange(len(agents))]
        v, c = heads[a]
        v, c = random_edit(rng, ol, a, v, c)
        heads[a] = (v, c)
        if rng.random() < 0.4:     # peers sync up sometimes
            merged = ol.checkout_tip()
            for a2 in agents:
                if rng.random() < 0.5:
                    heads[a2] = (list(merged.version), merged.snapshot())
        sess.sync()
        assert sess.text() == ol.checkout_tip().snapshot(), \
            f"seed {seed} diverged at step {step}"


def test_session_incremental_not_resyncing():
    """Sequential same-agent edits must stay on the incremental path
    (no resync after warm-up)."""
    ol = OpLog()
    a = ol.get_or_create_agent_id("solo")
    v = [ol.add_insert_at(a, [], 0, "hello world, this is a doc. ")]
    sess = DeviceZoneSession(ol)
    base_resyncs = sess.resyncs
    for i in range(10):
        v = [ol.add_insert_at(a, v, 5 + i, f"x{i}")]
        sess.sync()
    assert sess.resyncs == base_resyncs, "sequential edits caused resyncs"
    assert sess.text() == ol.checkout_tip().snapshot()


def test_session_two_agent_no_resync_after_warmup():
    """The friendsforever shape: two agents interleaving, each editing
    from its own head with periodic merges — after the first build the
    incremental path must handle everything (agent heads are pinned)."""
    rng = random.Random(4242)
    ol = OpLog()
    a1 = ol.get_or_create_agent_id("p1")
    a2 = ol.get_or_create_agent_id("p2")
    v = [ol.add_insert_at(a1, [], 0, "shared base text ")]
    h = {a1: (v, "shared base text "), a2: (v, "shared base text ")}
    for _ in range(6):
        for a in (a1, a2):
            vv, cc = h[a]
            vv, cc = random_edit(rng, ol, a, vv, cc)
            h[a] = (vv, cc)
    sess = DeviceZoneSession(ol, max_chars=64)
    base = sess.resyncs
    for step in range(20):
        a = (a1, a2)[step % 2]
        vv, cc = h[a]
        vv, cc = random_edit(rng, ol, a, vv, cc)
        h[a] = (vv, cc)
        if step % 5 == 4:
            m = ol.checkout_tip()
            h[a1] = h[a2] = (list(m.version), m.snapshot())
        sess.sync()
        assert sess.text() == ol.checkout_tip().snapshot()
    assert sess.resyncs == base, "realtime pattern fell off the " \
        "incremental path"


def test_session_capacity_growth_resync():
    """Slot-capacity overflow resyncs transparently."""
    ol = OpLog()
    a = ol.get_or_create_agent_id("big")
    v = [ol.add_insert_at(a, [], 0, "tiny")]
    sess = DeviceZoneSession(ol)
    v = [ol.add_insert_at(a, v, 2, "y" * (sess.W_cap + 10))]
    sess.sync()
    assert sess.text() == ol.checkout_tip().snapshot()


def test_session_root_anchored_op():
    """A concurrent op with parents=[] (root insert) must resync, not
    crash (regression: IndexError on empty source rows)."""
    ol = OpLog()
    a = ol.get_or_create_agent_id("a")
    b = ol.get_or_create_agent_id("b")
    ol.add_insert_at(a, [], 0, "first doc")
    sess = DeviceZoneSession(ol)
    ol.add_insert_at(b, [], 0, "root-concurrent")
    sess.sync()
    assert sess.text() == ol.checkout_tip().snapshot()


def test_session_late_agent_resync():
    """Registering a NEW agent shifts existing name ranks; the session
    must rebuild instead of mixing key epochs (regression: tie-breaks
    diverging from the host engine)."""
    ol = OpLog()
    a = ol.get_or_create_agent_id("mm")
    v = [ol.add_insert_at(a, [], 0, "base ")]
    sess = DeviceZoneSession(ol)
    # 'aa' sorts BEFORE 'mm': every existing rank shifts
    b = ol.get_or_create_agent_id("aa")
    z = ol.get_or_create_agent_id("zz")
    ol.add_insert_at(b, v, 2, "B")
    ol.add_insert_at(z, v, 2, "Z")
    ol.add_insert_at(a, v, 2, "M")
    sess.sync()
    assert sess.text() == ol.checkout_tip().snapshot()


def test_session_sliced_resync_matches_whole_tape(monkeypatch):
    """A resync executed as bounded-length slices (DT_SESSION_SLICE — the
    tpu default via auto_slice_steps, added because a grown session's
    whole-tape rebuild would cross the tunneled runtime's ~60 s
    per-program kill bound) is bit-identical to the whole-tape rebuild:
    same text, same incremental behavior afterwards."""
    rng = random.Random(9100)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("ann", "bo")]
    v, c = [], ""
    for _ in range(12):
        v, c = random_edit(rng, ol, agents[0], v, c)
    heads = {a: (v, c) for a in agents}
    for step in range(20):
        a = agents[step % 2]
        hv, hc = heads[a]
        heads[a] = random_edit(rng, ol, a, hv, hc)

    monkeypatch.setenv("DT_SESSION_SLICE", "7")   # uneven boundaries
    sess = DeviceZoneSession(ol)
    assert sess.text() == ol.checkout_tip().snapshot()
    # incremental continuation on top of a sliced rebuild
    for step in range(10):
        a = agents[step % 2]
        hv, hc = heads[a]
        heads[a] = random_edit(rng, ol, a, hv, hc)
        sess.sync()
        assert sess.text() == ol.checkout_tip().snapshot()
