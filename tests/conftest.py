import os
import sys

# Force a virtual 8-device CPU mesh for sharding tests; benches run separately
# on real TPU hardware (see bench.py which clears these).
os.environ["JAX_PLATFORMS"] = "cpu"  # virtual mesh for tests; bench.py uses the real chip
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's site hooks can force an accelerator platform regardless of
# the env var, so pin the platform via the config API too (must run before the
# backend initializes, i.e. before any jax.devices() call).
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REFERENCE_DIR = "/root/reference"


def reference_path(*parts):
    return os.path.join(REFERENCE_DIR, *parts)


import pytest


@pytest.fixture(autouse=True)
def _fresh_engine_policy():
    """Engine-selection measurements must not leak across tests: a zone
    rate recorded by one test could otherwise flip (or probe-flip) an
    unrelated later test's Branch.merge onto the zone engine — an
    ordering-dependent flake and, on big corpora, a CPU-backend stall."""
    from diamond_types_tpu.listmerge import policy
    saved = policy.GLOBAL
    policy.GLOBAL = policy.EnginePolicy()
    yield
    policy.GLOBAL = saved
