"""Adaptive-admission tests (qos/): the closed-loop deadline
controller, mesh-aware shedding, per-tenant token-bucket isolation,
the per-class admission queue wiring, and the dt_qos_* export surface
(prom families, /metrics + /debug/qos, scorecard block).

The controller tests run on a fake clock against a fake Observability
(a TimeSeries the test drives directly), so convergence and
hysteresis are deterministic. The e2e test boots a real server with
--qos semantics and uses the force_mesh_state hook to verify the
shed-before-interactive ordering over live HTTP.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from diamond_types_tpu.obs.prom import render_metrics
from diamond_types_tpu.obs.scorecard import build_scorecard, diff_scorecards
from diamond_types_tpu.obs.timeseries import TimeSeries
from diamond_types_tpu.qos import (QOS_CLASS_KEYS, QOS_CLASSES,
                                   QosController, ShedPolicy, TokenBucket,
                                   classify_headers, default_classes,
                                   merge_snapshots, tenant_of)
from diamond_types_tpu.qos.metrics import QosMetrics
from diamond_types_tpu.serve.admission import AdmissionQueue, Backpressure

pytestmark = pytest.mark.qos


class FakeClock:
    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class FakeObs:
    """Just enough Observability surface for QosController.step."""

    def __init__(self, ts) -> None:
        self.ts = ts


def make_controller(clock, flush_deadline_s=0.05, n_shards=1,
                    flush_docs=8, **kw):
    q = AdmissionQueue(n_shards, max_pending=64, flush_docs=flush_docs,
                      flush_deadline_s=flush_deadline_s)
    ctl = QosController(clock=clock, **kw)
    ctl.bind(q)
    ctl.attach_obs(FakeObs(TimeSeries(window_s=1.0, n_windows=600,
                                      clock=clock)))
    return ctl, q


# ---- taxonomy ------------------------------------------------------------

def test_classify_headers():
    assert classify_headers({"X-DT-QoS": "bulk"}) == "bulk"
    assert classify_headers({"X-DT-QoS": " Catchup "}) == "catchup"
    # unknown explicit value must not deprioritize a user edit
    assert classify_headers({"X-DT-QoS": "speedy"}) == "interactive"
    assert classify_headers({"X-DT-Replication": "1"}) == "catchup"
    assert classify_headers({"X-DT-QoS": "bulk",
                             "X-DT-Replication": "1"}) == "bulk"
    assert classify_headers({}) == "interactive"


def test_tenant_of_grammar():
    assert tenant_of("t0-doc001") == "t0"
    assert tenant_of("t17-bulk000") == "t17"
    assert tenant_of("bank0000007") is None
    assert tenant_of("tx-doc") is None
    assert tenant_of(None) is None


def test_default_classes_contract():
    classes = default_classes(0.05)
    inter = classes["interactive"]
    # interactive ceiling IS the static deadline: adaptive batching may
    # only ever tighten the latency-sensitive class
    assert inter.ceiling_s == 0.05 and not inter.sheddable
    assert classes["bulk"].sheddable and classes["catchup"].sheddable
    assert classes["bulk"].ceiling_s == pytest.approx(2.0)
    # clamp = floors/ceilings enforcement
    assert inter.clamp(10.0) == inter.ceiling_s
    assert inter.clamp(0.0) == inter.floor_s
    b = classes["bulk"]
    assert b.floor_s <= b.clamp(0.4) <= b.ceiling_s


# ---- the control loop (fake clock) ---------------------------------------

def test_controller_stretches_bulk_under_moderate_load():
    clock = FakeClock()
    ctl, q = make_controller(clock)
    base = ctl.classes["bulk"].deadline_s
    ts = ctl.metrics.ts
    for _ in range(40):
        ts.inc("qos.admitted.bulk", 5.0)   # ~20/s on the fake clock
        clock.advance(0.25)
        ctl.step()
    # gap=8 docs at 20/s => ~0.4s fill time > the 0.25s base deadline
    got = ctl.effective_deadline(0, "bulk")
    assert got > base * 1.2
    assert got <= ctl.classes["bulk"].ceiling_s
    assert ctl.metrics.snapshot()["controller"]["stretched"] >= 1


def test_controller_shrinks_to_floor_when_idle():
    clock = FakeClock()
    ctl, q = make_controller(clock)
    ts = ctl.metrics.ts
    for _ in range(20):
        ts.inc("qos.admitted.bulk", 5.0)
        clock.advance(0.25)
        ctl.step()
    stretched = ctl.effective_deadline(0, "bulk")
    # arrivals stop; once the rate window drains, fill time is
    # unreachable and the deadline drops to the floor — lone docs
    # flush early instead of paying occupancy nobody will deliver
    for _ in range(60):
        clock.advance(0.25)
        ctl.step()
    floor = ctl.classes["bulk"].floor_s
    got = ctl.effective_deadline(0, "bulk")
    assert got < stretched
    assert got == pytest.approx(floor, rel=0.25)


def test_controller_hysteresis_holds_on_noise():
    clock = FakeClock()
    ctl, q = make_controller(clock, deadband=0.1)
    ts = ctl.metrics.ts
    for _ in range(40):
        ts.inc("qos.admitted.bulk", 5.0)
        clock.advance(0.25)
        ctl.step()
    before = ctl.metrics.snapshot()["controller"]
    # +/-5% oscillation around the converged rate sits inside the 10%
    # deadband: the published table must hold, not thrash
    for i in range(40):
        ts.inc("qos.admitted.bulk", 5.25 if i % 2 else 4.75)
        clock.advance(0.25)
        ctl.step()
    after = ctl.metrics.snapshot()["controller"]
    held = after["held"] - before["held"]
    moved = (after["stretched"] - before["stretched"]) \
        + (after["shrunk"] - before["shrunk"])
    assert held > moved * 3


def test_slo_guard_pins_class_to_floor():
    clock = FakeClock()
    ctl, q = make_controller(clock)

    class BurnSlo:
        def evaluate(self):
            return [{"name": "queue_wait_p99", "state": "burning",
                     "fast": {"burn": 20.0}}]

    ctl.obs.slo = BurnSlo()
    ts = ctl.metrics.ts
    for _ in range(40):
        ts.inc("qos.admitted.bulk", 5.0)   # load that would stretch
        clock.advance(0.25)
        ctl.step()
    # bulk's objective burns => latency wins over occupancy
    assert ctl.effective_deadline(0, "bulk") == pytest.approx(
        ctl.classes["bulk"].floor_s, rel=0.25)
    assert ctl.metrics.snapshot()["controller"]["floors"] > 0


def test_interactive_never_exceeds_static_deadline():
    clock = FakeClock()
    ctl, q = make_controller(clock, flush_deadline_s=0.05)
    ts = ctl.metrics.ts
    for _ in range(60):
        # slow interactive trickle: naive fill-time would say "wait
        # seconds"; the ceiling must cap it at the static deadline
        ts.inc("qos.admitted.interactive", 0.5)
        clock.advance(0.25)
        ctl.step()
    assert ctl.effective_deadline(0, "interactive") <= 0.05 + 1e-9


def test_mesh_warning_pins_sheddable_to_ceiling():
    clock = FakeClock()
    ctl, q = make_controller(clock)
    ctl.force_mesh_state("warning", retry_after=0.0)
    for _ in range(40):
        clock.advance(0.25)
        ctl.step()
    assert ctl.effective_deadline(0, "bulk") == pytest.approx(
        ctl.classes["bulk"].ceiling_s, rel=0.2)
    # interactive is not sheddable: the warning leaves it alone
    assert ctl.effective_deadline(0, "interactive") <= 0.05 + 1e-9
    assert ctl.metrics.snapshot()["controller"]["ceilings"] > 0


# ---- shed policy ---------------------------------------------------------

def _burning_rows(burn=14.4):
    return [{"name": "visibility_p99", "state": "burning",
             "fast": {"burn": burn, "bad": 10, "total": 20}}]


def test_shed_orders_sheddable_before_interactive():
    clock = FakeClock()
    pol = ShedPolicy(metrics=QosMetrics(), clock=clock)
    pol.refresh(_burning_rows())
    ok_b, retry_b, why_b = pol.admit("bulk")
    ok_c, retry_c, why_c = pol.admit("catchup")
    ok_i, retry_i, why_i = pol.admit("interactive")
    assert not ok_b and not ok_c
    assert why_b.startswith("mesh_burn") and "visibility_p99" in why_b
    assert retry_b > 0 and retry_c > 0
    # the invariant the gate is named for: interactive survives while
    # the sheddable classes take the 429s
    assert ok_i and retry_i == 0.0
    snap = pol.metrics.snapshot()["classes"]
    assert snap["bulk"]["shed"] == 1 and snap["catchup"]["shed"] == 1
    assert snap["interactive"]["shed"] == 0


def test_shed_retry_after_scales_with_burn_and_clamps():
    pol = ShedPolicy()
    pol.refresh(_burning_rows(burn=2.0))
    assert pol.admit("bulk")[1] == pytest.approx(0.5)
    pol.refresh(_burning_rows(burn=1000.0))
    assert pol.admit("bulk")[1] == 10.0      # ceiling
    pol.refresh(_burning_rows(burn=0.1))
    assert pol.admit("bulk")[1] == 0.25      # floor


def test_warning_defers_instead_of_shedding():
    pol = ShedPolicy(metrics=QosMetrics())
    pol.refresh([{"name": "visibility_p99", "state": "warning",
                  "fast": {"burn": 2.0}}])
    ok, retry, why = pol.admit("bulk")
    assert ok and why == "deferred"
    assert pol.metrics.snapshot()["classes"]["bulk"]["deferred"] == 1


def test_convergence_lag_trips_mesh_gate():
    pol = ShedPolicy(lag_threshold_s=10.0)
    pol.refresh([], lag={"peer-b": {"mean_s": 30.0, "max_s": 60.0,
                                    "n": 4}})
    ok, retry, why = pol.admit("catchup")
    assert not ok and "convergence_lag:peer-b" in why


def test_token_bucket_refill():
    tb = TokenBucket(rate=10.0, burst=2.0, now=0.0)
    assert tb.take(0.0) and tb.take(0.0) and not tb.take(0.0)
    assert tb.take(0.1)                      # 1 token refilled
    assert not tb.take(0.1)


def test_hot_tenant_isolated_without_collateral():
    clock = FakeClock()
    pol = ShedPolicy(metrics=QosMetrics(), tenant_rate=100.0,
                     tenant_burst=10.0, isolation_factor=0.1,
                     clock=clock)
    pol.refresh([], hot_tenants={"t0"})
    # hot tenant gets burst*0.1 = 1 token; neighbor keeps its full 10
    assert pol.admit("interactive", tenant="t0")[0]
    ok, retry, why = pol.admit("interactive", tenant="t0")
    assert not ok and why == "tenant" and retry > 0
    for _ in range(10):
        assert pol.admit("interactive", tenant="t1")[0]


def test_hot_set_from_attrib_top_share():
    class Attrib:
        def top(self, dim, kind, n):
            return [("t9-doc000", 80.0, 0), ("t1-doc000", 10.0, 0),
                    ("bank0001", 10.0, 0)]

    pol = ShedPolicy(hot_share=0.5)
    assert pol.hot_tenants_from_attrib(Attrib()) == frozenset({"t9"})


# ---- admission queue wiring ----------------------------------------------

def test_queue_static_path_identical_when_detached():
    # no controller: every class sees the static trigger, the qos
    # field rides along inert
    q = AdmissionQueue(1, max_pending=8, flush_docs=4,
                       flush_deadline_s=0.05)
    q.submit(0, "a", 1, now=0.0, qos="bulk")
    q.submit(0, "b", 1, now=0.0)
    assert q.due(0.04) == []
    assert q.due(0.051) == [(0, 1, "deadline")]
    items = q.take(0, 1)
    assert [i.qos for i in items] == ["bulk", "interactive"]
    assert q.class_depth(0, "bulk") == 0


class StubCtl:
    """Published-table stand-in: per-class deadlines, full budgets."""

    def __init__(self, table):
        self.table = table

    def effective_deadline(self, shard, cls):
        return self.table[cls]

    def depth_budget(self, cls, max_pending):
        return max_pending


def test_queue_deadline_trigger_consults_controller_per_class():
    q = AdmissionQueue(1, max_pending=8, flush_docs=4,
                       flush_deadline_s=0.05)
    q.qos = StubCtl({"interactive": 0.01, "bulk": 0.5})
    q.submit(0, "bulky", 3, now=0.0, qos="bulk")       # bucket 4
    q.submit(0, "quick", 1, now=0.0, qos="interactive")  # bucket 1
    # interactive fires at its tightened deadline, bulk keeps waiting
    assert q.due(0.02) == [(0, 1, "deadline")]
    assert (0, 4, "deadline") in q.due(0.6)


def test_queue_mixed_bucket_interactive_not_starved_by_bulk():
    # regression: an interactive doc enqueued BEHIND a bulk doc in the
    # SAME shape bucket must flush on the interactive deadline, not
    # wait out the bulk item's stretched one — due() consults every
    # class's oldest entry, not just the first-inserted item's class
    q = AdmissionQueue(1, max_pending=8, flush_docs=4,
                       flush_deadline_s=0.05)
    q.qos = StubCtl({"interactive": 0.05, "bulk": 2.0})
    q.submit(0, "bulky", 1, now=0.0, qos="bulk")
    q.submit(0, "quick", 1, now=0.1, qos="interactive")
    assert q.due(0.1) == []
    # fires at the interactive item's own deadline (0.1 + 0.05), far
    # before bulk's stretched 2.0s window elapses
    assert q.due(0.16) == [(0, 1, "deadline")]


def test_queue_coalesced_entry_keeps_deadline_seniority():
    # a coalescing re-submit re-inserts at the dict tail but keeps the
    # ORIGINAL enqueue time; the deadline trigger must still see it as
    # the bucket's most-waited entry
    q = AdmissionQueue(1, max_pending=8, flush_docs=8,
                       flush_deadline_s=0.05)
    q.submit(0, "a", 3, now=0.0)            # bucket 4
    q.submit(0, "b", 3, now=0.04)           # bucket 4, younger
    q.submit(0, "a", 1, now=0.045)          # coalesce: a -> dict tail
    assert q.due(0.051) == [(0, 4, "deadline")]


def test_queue_coalesce_upgrades_to_urgent_class():
    q = AdmissionQueue(1, max_pending=8, flush_docs=4,
                       flush_deadline_s=0.05)
    q.qos = StubCtl({"interactive": 0.01, "bulk": 10.0})
    q.submit(0, "d", 1, now=0.0, qos="bulk")
    assert q.class_depth(0, "bulk") == 1
    # an interactive re-touch must not wait out the bulk deadline
    q.submit(0, "d", 1, now=0.0, qos="interactive")
    assert q.class_depth(0, "bulk") == 0
    assert q.class_depth(0, "interactive") == 1
    assert q.due(0.02) == [(0, 2, "deadline")]
    # the reverse direction never downgrades
    q.submit(0, "d", 1, now=0.0, qos="catchup")
    assert q.class_depth(0, "interactive") == 1


def test_queue_per_class_depth_budget():
    class Budgeted(StubCtl):
        def depth_budget(self, cls, max_pending):
            return 2 if cls == "bulk" else max_pending

    q = AdmissionQueue(1, max_pending=8, flush_docs=4,
                       flush_deadline_s=0.05)
    q.qos = Budgeted({"interactive": 0.05, "bulk": 0.5})
    q.submit(0, "b1", 1, now=0.0, qos="bulk")
    q.submit(0, "b2", 1, now=0.0, qos="bulk")
    with pytest.raises(Backpressure):
        q.submit(0, "b3", 1, now=0.0, qos="bulk")
    # the bulk budget must not take interactive admission down with it
    q.submit(0, "i1", 1, now=0.0, qos="interactive")


# ---- metrics + export surface --------------------------------------------

def test_merge_snapshots_sums_and_maxes():
    a, b = QosMetrics(), QosMetrics()
    a.bump_class("bulk", "admitted", 3)
    a.set_deadline("bulk", 0.4)
    b.bump_class("bulk", "admitted", 2)
    b.bump_class("bulk", "shed")
    b.set_deadline("bulk", 0.9)
    merged = merge_snapshots([a.snapshot(), None, b.snapshot()])
    assert merged["classes"]["bulk"]["admitted"] == 5
    assert merged["classes"]["bulk"]["shed"] == 1
    assert merged["classes"]["bulk"]["deadline_s"] == pytest.approx(0.9)
    assert merge_snapshots([None, None]) is None


def test_prom_qos_families_zero_filled_when_idle():
    clock = FakeClock()
    ctl, _q = make_controller(clock)
    text = render_metrics({"qos": ctl.export()})
    # an idle controller still exports every (key, class) series
    for key in QOS_CLASS_KEYS:
        for cls in QOS_CLASSES:
            assert f'dt_qos_{key}_total{{class="{cls}"}} 0' in text
    assert 'dt_qos_deadline_seconds{class="interactive"}' in text
    assert 'dt_qos_controller_total{decision="steps"} 0' in text
    assert "dt_qos_enabled 1" in text
    assert "dt_qos_mesh_state 0" in text
    # prom shape validity: one TYPE per family, no duplicate samples
    seen_types, seen_samples = set(), set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE"):
            fam = line.split()[2]
            assert fam not in seen_types
            seen_types.add(fam)
        elif not line.startswith("#"):
            key = line.rsplit(" ", 1)[0]
            assert key not in seen_samples, key
            seen_samples.add(key)


def test_scorecard_qos_block_optional_and_ungated():
    kw = dict(scenario={"name": "x"}, wall_s=1.0, virtual_s=1.0,
              totals={"ops": 10}, latency_p99_s={})
    plain = build_scorecard(**kw)
    assert "qos" not in plain
    snap = QosMetrics().snapshot()
    carded = build_scorecard(qos=snap, **kw)
    assert carded["qos"]["schema_version"] == 1
    # a qos block appearing on the new side must never gate a diff
    # against a pre-QoS baseline
    diff = diff_scorecards(plain, carded)
    assert diff["ok"], diff["regressions"]


# ---- end to end over HTTP ------------------------------------------------

def _post(base, doc, body=None, headers=None):
    payload = json.dumps(body or {"agent": "qa", "version": [],
                                  "ops": [{"kind": "ins", "pos": 0,
                                           "text": "hi "}]})
    req = urllib.request.Request(f"{base}/doc/{doc}/edit",
                                 data=payload.encode("utf8"))
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def test_server_shed_gate_and_debug_endpoint():
    from diamond_types_tpu.tools.server import serve
    srv = serve(port=0, data_dir=None, serve_shards=2, qos=True)
    port = srv.server_address[1]
    base = f"http://127.0.0.1:{port}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        qctl = srv.store.scheduler.qos
        assert qctl is not None

        # healthy mesh: everything admits, the class rides the queue
        st, _ = _post(base, "t0-doc000")
        assert st == 200
        st, _ = _post(base, "t0-doc000", headers={"X-DT-QoS": "bulk"})
        assert st == 200

        # force the mesh gate to burning: bulk 429s with Retry-After,
        # interactive still lands — shed BEFORE interactive degrades
        qctl.force_mesh_state("burning", retry_after=1.5)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "t0-doc001", headers={"X-DT-QoS": "bulk"})
        err = ei.value
        assert err.code == 429
        assert float(err.headers["Retry-After"]) == pytest.approx(1.5)
        detail = json.loads(err.read())
        assert detail["qos"] == "bulk"
        assert detail["reason"].startswith("mesh_burn")
        err.close()
        st, _ = _post(base, "t0-doc001")
        assert st == 200
        qctl.force_mesh_state(None)

        # /debug/qos + the /metrics qos block + prom render
        with urllib.request.urlopen(f"{base}/debug/qos",
                                    timeout=5) as r:
            dbg = json.loads(r.read())
        assert dbg["enabled"] and dbg["running"]
        assert dbg["classes"]["bulk"]["admitted"] >= 1
        assert dbg["classes"]["bulk"]["shed"] >= 1
        assert dbg["classes"]["interactive"]["shed"] == 0
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["qos"]["classes"]["interactive"]["admitted"] >= 2
        text = render_metrics(doc)
        assert 'dt_qos_shed_total{class="bulk"} ' in text
    finally:
        srv.shutdown()
        srv.server_close()


def test_server_qos_off_has_no_block():
    from diamond_types_tpu.tools.server import serve
    srv = serve(port=0, data_dir=None, serve_shards=1)
    port = srv.server_address[1]
    base = f"http://127.0.0.1:{port}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        st, _ = _post(base, "t0-doc000", headers={"X-DT-QoS": "bulk"})
        assert st == 200
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["qos"] is None
        with urllib.request.urlopen(f"{base}/debug/qos",
                                    timeout=5) as r:
            assert json.loads(r.read()) == {"enabled": False}
        assert "dt_qos_" not in render_metrics(doc)
    finally:
        srv.shutdown()
        srv.server_close()


# ---- scenario integration ------------------------------------------------

def test_smoke_scenario_with_qos_stamps_block():
    from diamond_types_tpu.workload import get_scenario
    from diamond_types_tpu.workload.runner import run_scenario
    card = run_scenario(get_scenario("smoke"), qos=True)
    assert card["ok"], card["slo"]
    qos = card["qos"]
    assert qos["schema_version"] == 1
    assert qos["classes"]["interactive"]["admitted"] > 0
    assert qos["classes"]["bulk"]["admitted"] > 0
    # a healthy smoke run never sheds
    assert all(row["shed"] == 0 for row in qos["classes"].values())
    assert qos["sheds_observed"] == 0
    assert qos["controller"]["steps"] > 0


@pytest.mark.slow
def test_flash_crowd_qos_ab_smoke():
    """A/B: adaptive admission on the QoS stressor must stay
    convergent and not regress against its own static control arm
    past the scorecard bands."""
    import dataclasses

    from diamond_types_tpu.workload import get_scenario
    from diamond_types_tpu.workload.runner import run_scenario
    sc = dataclasses.replace(get_scenario("flash-crowd"),
                             duration_s=8.0)
    control = run_scenario(sc)
    adaptive = run_scenario(sc, qos=True)
    assert "qos" not in control and adaptive["qos"] is not None
    assert adaptive["convergence"]["converged"]
    diff = diff_scorecards(control, adaptive)
    assert diff["ok"], diff["regressions"]
