"""Device-resident tail transform (tpu/xform.py) + the Pallas replay rung.

Covers the ISSUE-13 tentpole surface: randomized mixed-bucket parity of
the device-planned transform against the host tracker walk (byte-
identical final text), a 64-way concurrent merge resolved on device, the
log-prefix-frontier contract proven by the DAG reachability kernel,
per-doc poison isolation on the device-plan rung, the five-rung fallback
ladder (pallas -> mesh -> fused -> per-doc -> host) surviving injected
rung failures with parity intact, warmup coverage for the xform/pallas
jit families, and the --device-plan / --pallas CLI flags. CPU-simulated
devices via conftest's virtual 8-device mesh; Pallas kernels run in
interpret mode off-TPU.
"""

import random

import numpy as np
import pytest

from diamond_types_tpu.serve.metrics import ServeMetrics
from diamond_types_tpu.serve.scheduler import MergeScheduler
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.tpu import flush_fuse as ff
from diamond_types_tpu.tpu import xform as xfm

pytestmark = [pytest.mark.fused, pytest.mark.serve]

FUSED_OPTS = {"cap": 256, "max_ins": 4}


def _mk_oplog(doc_id: str) -> OpLog:
    ol = OpLog()
    ol.doc_id = doc_id
    return ol


def _random_edits(ol: OpLog, rng: random.Random, n: int,
                  agent: str = "a") -> None:
    a = ol.get_or_create_agent_id(agent)
    for _ in range(n):
        cur = len(ol.checkout_tip().snapshot())
        if cur and rng.random() < 0.3:
            pos = rng.randrange(cur)
            end = min(pos + rng.randint(1, 9), cur)
            ol.add_delete_without_content(a, pos, end)
        else:
            pos = rng.randint(0, cur)
            s = "".join(rng.choice("abcdefgh") for _ in
                        range(rng.randint(1, 11)))
            ol.add_insert(a, pos, s)


def _mk_sched(ols, n_shards, **kw):
    kw.setdefault("engine", "device")
    kw.setdefault("fused", True)
    kw.setdefault("fused_opts", FUSED_OPTS)
    kw.setdefault("flush_docs", 8)
    kw.setdefault("flush_deadline_s", 10.0)
    kw.setdefault("flush_workers", False)
    return MergeScheduler(n_shards, resolve=lambda d: ols[d], **kw)


# ---- randomized mixed-bucket parity ---------------------------------------

def test_device_plan_parity_randomized_mixed_buckets(monkeypatch):
    """plan_tails_device == host tracker walk, byte-for-byte, on
    randomized mixed-size buckets with concurrent branches every round.
    DT_XFORM_VALIDATE=1 additionally proves the log-prefix-frontier
    threshold with the device reachability kernel on every extract."""
    monkeypatch.setenv("DT_XFORM_VALIDATE", "1")
    rng = random.Random(13)
    ols = [_mk_oplog(f"d{i}") for i in range(5)]
    for i, ol in enumerate(ols):
        _random_edits(ol, rng, 2 + i)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    total_dev = 0
    for rnd in range(4):
        for i, ol in enumerate(ols):
            _random_edits(ol, rng, 1 + (i + rnd) % 3)
            # a concurrent branch forked at the root: a genuine
            # conflict zone for the device resolver every round
            b = ol.get_or_create_agent_id("b")
            ol.add_insert_at(b, [], 0, "Z" * (1 + (i + rnd) % 2))
        plans, stats = xfm.plan_tails_device(sess)
        assert len(plans) == len(sess)
        assert all(p is not None for p in plans)
        total_dev += stats["device_docs"]
        fits = [p.fits(s.cap) for p, s in zip(plans, sess)]
        assert all(fits)
        ok, _dev = ff.fused_replay(sess, plans)
        assert all(ok)
        for s, ol in zip(sess, ols):
            assert s.text() == ol.checkout_tip().snapshot()
    # the device rung did the planning, not the host fallback
    assert total_dev >= len(sess)


def test_64_way_concurrent_merge_device_planned():
    """64 agents insert concurrently from the same frontier; the device
    transform resolves the full Fugue order in one dispatch and the
    replayed text matches the host oracle."""
    ol = _mk_oplog("wide")
    a0 = ol.get_or_create_agent_id("seed")
    ol.add_insert(a0, 0, "base ")
    sess = ff.FusedDocSession(ol, cap=1024, max_ins=4)
    base = list(ol.version)
    for k in range(64):
        ag = ol.get_or_create_agent_id(f"w{k}")
        ol.add_insert_at(ag, base, 0, f"[{k:02d}]")
    plans, stats = xfm.plan_tails_device([sess])
    assert stats["device_docs"] == 1 and stats["fallbacks"] == 0
    assert plans[0].fits(sess.cap)
    ok, _dev = ff.fused_replay([sess], plans)
    assert all(ok)
    assert sess.text() == ol.checkout_tip().snapshot()


def test_validate_prefix_frontier_threshold():
    """The contract old-visibility rests on: `lv < synced_to` iff the
    session frontier contains lv — proven by the scatter-max DAG
    reachability kernel, and violated by an off-by-one threshold."""
    ol = _mk_oplog("v")
    a = ol.get_or_create_agent_id("a")
    ol.add_insert(a, 0, "hello")
    sess = ff.FusedDocSession(ol, **FUSED_OPTS)
    b = ol.get_or_create_agent_id("b")
    ol.add_insert_at(b, [], 0, "XY")          # concurrent tail
    assert xfm.validate_prefix_frontier(ol, sess.frontier, sess.synced_to)
    assert not xfm.validate_prefix_frontier(ol, sess.frontier,
                                            sess.synced_to - 1)
    empty = _mk_oplog("e")
    assert xfm.validate_prefix_frontier(empty, (), 0)


# ---- per-doc poison isolation ---------------------------------------------

def test_per_doc_poison_isolation_on_device_plan_rung():
    """A contract violation in one device-planned doc poisons only ITS
    row: bucket neighbors commit and stay byte-correct."""
    rng = random.Random(23)
    ols = [_mk_oplog(f"p{i}") for i in range(3)]
    for ol in ols:
        a = ol.get_or_create_agent_id("a")
        ol.add_insert(a, 0, "seed ")
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for ol in ols:
        _random_edits(ol, rng, 2)
        b = ol.get_or_create_agent_id("b")
        ol.add_insert_at(b, [], 0, "Q")
    plans, stats = xfm.plan_tails_device(sess)
    assert stats["device_docs"] == 3
    assert plans[1].n_ops > 0
    plans[1].ilen[0] = FUSED_OPTS["max_ins"] + 1   # violates the contract
    ok, _dev = ff.fused_replay(sess, plans)
    assert ok == [True, False, True]
    for i in (0, 2):
        assert sess[i].text() == ols[i].checkout_tip().snapshot()


# ---- the fallback ladder under injected faults ----------------------------

def test_bank_pallas_rung_falls_back_to_fused(monkeypatch):
    """Injected pallas_fused_replay failure: the bank's `_replay_group`
    drops one rung to the fused replay, bumps `pallas_fallbacks`, and
    parity holds — nothing is lost, nothing is bypassed."""
    ols = {}
    sched = _mk_sched(ols, 1, device_plan=True, pallas=True)
    assert sched.banks[0].pallas
    rng = random.Random(31)
    docs = [f"d{i}" for i in range(4)]
    for rnd in range(3):
        for d in docs:
            if rnd == 0:
                ols[d] = _mk_oplog(d)
            _random_edits(ols[d], rng, 2)
            assert sched.submit(d, n_ops=2)["accepted"]
        if rnd == 2:
            def boom(sessions, plans):
                raise RuntimeError("injected pallas failure")
            monkeypatch.setattr(ff, "pallas_fused_replay", boom)
        sched.pump(force=True)
    monkeypatch.undo()
    m = sched.metrics_json()
    assert m["totals"]["pallas_fallbacks"] >= 1
    assert m["totals"]["host_fallbacks"] == 0
    for d in docs:
        assert sched.text(d) == ols[d].checkout_tip().snapshot()


def test_window_ladder_pallas_then_mesh_rungs_fail(monkeypatch):
    """Mesh flush window with BOTH top rungs failing (pallas raise,
    mesh raise): the window completes through the per-shard fused
    fallback with byte parity — the ladder never bypasses a fence."""
    from diamond_types_tpu.parallel import mesh as pm
    ols = {}
    sched = _mk_sched(ols, 1, mesh_window=True, device_plan=True,
                      pallas=True)
    rng = random.Random(37)
    docs = [f"d{i}" for i in range(4)]
    for rnd in range(3):
        for d in docs:
            if rnd == 0:
                ols[d] = _mk_oplog(d)
            _random_edits(ols[d], rng, 2)
            assert sched.submit(d, n_ops=2)["accepted"]
        if rnd == 2:
            def boom(*a, **k):
                raise RuntimeError("injected rung failure")
            # both call-time imports re-resolve these module attrs
            monkeypatch.setattr(ff, "pallas_fused_replay", boom)
            monkeypatch.setattr(pm, "mesh_fused_replay", boom)
        sched.pump(force=True)
    monkeypatch.undo()
    m = sched.metrics_json()
    assert m["window"]["windows"] >= 3
    for d in docs:
        assert sched.text(d) == ols[d].checkout_tip().snapshot()


def test_device_plan_guard_trip_host_fallback(monkeypatch):
    """An extract whose device resolution fails (injected) is re-planned
    by the host tracker walk per doc — counted as a transform fallback,
    with parity intact (the per-doc host rung of the transform ladder)."""
    rng = random.Random(41)
    ols = [_mk_oplog(f"g{i}") for i in range(3)]
    for ol in ols:
        _random_edits(ol, rng, 3)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for ol in ols:
        _random_edits(ol, rng, 2)
        b = ol.get_or_create_agent_id("b")
        ol.add_insert_at(b, [], 0, "W")
    monkeypatch.setattr(xfm, "resolve_positions",
                        lambda exts: [None] * len(exts))
    plans, stats = xfm.plan_tails_device(sess)
    monkeypatch.undo()
    assert stats["fallbacks"] == 3 and stats["device_docs"] == 0
    assert all(p is not None for p in plans)
    ok, _dev = ff.fused_replay(sess, plans)
    assert all(ok)
    for s, ol in zip(sess, ols):
        assert s.text() == ol.checkout_tip().snapshot()


# ---- warmup coverage ------------------------------------------------------

def test_warmup_precompiles_xform_and_pallas_classes():
    """warmup_fused_cache(xform_classes=..., pallas=True) compiles the
    transform dispatch and the Pallas replay rung; a second warmup over
    the same shapes is ALL cache hits (zero new misses)."""
    from diamond_types_tpu.obs.devprof import PROFILER
    PROFILER.reset()
    PROFILER.enabled = True
    try:
        n = ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                                  shape_classes=(1,), xform_classes=(1,),
                                  pallas=True)
        # batches {1, 2} x one shape class, for fused + xform + pallas
        assert n == 6
        snap1 = PROFILER.snapshot()["jit_cache"]
        assert snap1["xform"]["misses"] == 2
        assert snap1["pallas"]["misses"] == 2
        ff.warmup_fused_cache(flush_docs=2, cap=64, max_ins=2,
                              shape_classes=(1,), xform_classes=(1,),
                              pallas=True)
        snap2 = PROFILER.snapshot()["jit_cache"]
        for fam in ("fused", "xform", "pallas"):
            assert snap2[fam]["misses"] == snap1[fam]["misses"], fam
            assert snap2[fam]["hits"] >= snap1[fam]["hits"] + 2, fam
    finally:
        PROFILER.enabled = False


# ---- Pallas kernels (interpret mode off-TPU) ------------------------------

@pytest.mark.pallas
def test_xform_positions_pallas_parity():
    """The gather-free position-resolution kernel == the jnp cumsum
    formulation across lane-boundary sizes (Mosaic's ~128-lane gather
    cap is why the kernel exists)."""
    from diamond_types_tpu.tpu.pallas_kernels import xform_positions_pallas
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    for n in (1, 5, 127, 128, 513):
        nv = rng.integers(0, 6, n).astype(np.int32)
        ov = rng.integers(0, 6, n).astype(np.int32)
        pos, new_len, peak = xform_positions_pallas(
            jnp.asarray(nv), jnp.asarray(ov), interpret=True)
        cum = np.cumsum(nv)
        assert (np.asarray(pos)[:n] == (cum - nv)).all(), n
        assert int(new_len) == int(nv.sum()), n
        want_peak = max(0, int(np.max(np.cumsum(
            nv.astype(np.int64) - ov))))
        assert int(peak) == want_peak, n


@pytest.mark.pallas
def test_pallas_fused_replay_parity():
    """The ladder's top rung == host checkout on randomized concurrent
    buckets (step kernel in interpret mode on the CPU backend)."""
    rng = random.Random(43)
    ols = [_mk_oplog(f"pl{i}") for i in range(3)]
    for i, ol in enumerate(ols):
        _random_edits(ol, rng, 2 + i)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for rnd in range(2):
        for i, ol in enumerate(ols):
            _random_edits(ol, rng, 1 + (i + rnd) % 2)
            b = ol.get_or_create_agent_id("b")
            ol.add_insert_at(b, [], 0, "Y" * (i + 1))
        plans = [s.plan_tail() for s in sess]
        ok, _dev = ff.pallas_fused_replay(sess, plans)
        assert all(ok)
        for s, ol in zip(sess, ols):
            assert s.text() == ol.checkout_tip().snapshot()


@pytest.mark.pallas
def test_pallas_xform_end_to_end(monkeypatch):
    """DT_TPU_PALLAS=1 routes the transform's position scans through the
    Pallas kernel; the device-planned replay stays byte-identical."""
    monkeypatch.setenv("DT_TPU_PALLAS", "1")
    ol = _mk_oplog("pe")
    a = ol.get_or_create_agent_id("a")
    ol.add_insert(a, 0, "root ")
    sess = ff.FusedDocSession(ol, **FUSED_OPTS)
    base = list(ol.version)
    for k in range(5):
        ag = ol.get_or_create_agent_id(f"c{k}")
        ol.add_insert_at(ag, base, 0, f"<{k}>")
    plans, stats = xfm.plan_tails_device([sess])
    assert stats["device_docs"] == 1
    ok, _dev = ff.fused_replay([sess], plans)
    assert all(ok)
    assert sess.text() == ol.checkout_tip().snapshot()


# ---- metrics + prom -------------------------------------------------------

def test_metrics_transform_block_and_version():
    m = ServeMetrics(2, 4, 64)
    m.record_transform(0, device_docs=3, host_docs=1, fallbacks=1,
                       batches=1)
    m.bump(0, "pallas_fallbacks")
    s = m.snapshot()
    assert s["version"] == 13
    t = s["transform"]
    assert t["device_docs"] == 3 and t["host_docs"] == 1
    assert t["fallbacks"] == 1 and t["batches"] == 1
    assert t["device_ratio"] == 0.6          # 3 / (3 + 1 + 1)
    assert s["totals"]["pallas_fallbacks"] == 1


def test_prom_zero_fills_xform_and_pallas_jit_families():
    """A devprof snapshot that never touched the xform/pallas caches
    still renders their jit families at 0 — dashboards keyed on the
    label set survive a host-plan-only deployment."""
    from diamond_types_tpu.obs.prom import render_metrics
    text = render_metrics({"obs": {"devprof": {
        "jit_cache": {"fused": {"hits": 3, "misses": 1}}}}})
    assert 'dt_devprof_jit_hits_total{cache="fused"} 3' in text
    assert 'dt_devprof_jit_hits_total{cache="xform"} 0' in text
    assert 'dt_devprof_jit_misses_total{cache="xform"} 0' in text
    assert 'dt_devprof_jit_hits_total{cache="pallas"} 0' in text


# ---- scheduler + driver + CLI ---------------------------------------------

def test_scheduler_device_plan_parity_vs_host_plan():
    """Identical concurrent edit streams through a device-plan scheduler
    and a host-plan control: every doc byte-identical, and the transform
    block shows the device rung actually engaged."""
    def mk_logs():
        logs = {}
        for i in range(6):
            ol = _mk_oplog(f"d{i}")
            a = ol.get_or_create_agent_id("seed")
            ol.add_insert(a, 0, f"doc{i}: ")
            logs[f"d{i}"] = ol
        return logs

    logs = [mk_logs() for _ in range(2)]
    scheds = [
        _mk_sched(logs[0], 2, device_plan=True, pallas=True),
        _mk_sched(logs[1], 2),
    ]
    assert scheds[0].device_plan and not scheds[1].device_plan
    rngs = [random.Random(19) for _ in range(2)]
    for rnd in range(4):
        for i in range(6):
            d = f"d{i}"
            for lg, r in zip(logs, rngs):
                _random_edits(lg[d], r, 2)
                if rnd >= 1:
                    b = lg[d].get_or_create_agent_id("b")
                    b_txt = "B" * (1 + (i + rnd) % 2)
                    lg[d].add_insert_at(b, [], 0, b_txt)
            for s in scheds:
                assert s.submit(d, n_ops=2)["accepted"]
        for s in scheds:
            s.pump(force=True)
    for i in range(6):
        d = f"d{i}"
        texts = [s.text(d) for s in scheds]
        assert texts[0] == texts[1]
        assert texts[0] == logs[0][d].checkout_tip().snapshot()
    t = scheds[0].metrics_json()["transform"]
    assert t["device_docs"] > 0
    assert t["batches"] > 0
    tc = scheds[1].metrics_json()["transform"]
    assert tc["device_docs"] == 0            # the control never engaged


def test_serve_bench_device_plan_smoke():
    """End-to-end driver run with the full ladder on: parity gate plus
    the transform block reporting device-planned docs."""
    from diamond_types_tpu.serve.driver import run_serve_bench
    report = run_serve_bench(shards=2, docs=4, txns=3, engine="device",
                             mode="concurrent", flush_docs=2,
                             max_sessions=8, steady_rounds=4,
                             device_plan=True, pallas=True,
                             warmup=False)
    assert report["parity_ok"], report["parity_mismatches"]
    assert report["config"]["device_plan"] and report["config"]["pallas"]
    t = report["transform"]
    assert t["device_docs"] > 0
    assert t["device_ratio"] > 0


def test_cli_device_plan_flags_smoke(capsys):
    """--device-plan/--pallas (and their --no- forms) parse and ride
    through the dry-run preset."""
    from diamond_types_tpu.tools.cli import main
    rc = main(["serve-bench", "--dry-run", "--device-plan", "--pallas",
               "--no-workers", "--steady-rounds", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "parity OK" in out
