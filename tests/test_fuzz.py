"""Randomized fuzzers — the correctness backbone, mirroring the reference's
test strategy (reference: src/listmerge/fuzzer.rs, src/list_fuzzer_tools.rs):
seeded RNG, random edits, convergence + oracle assertions.
"""

import random

import pytest

from diamond_types_tpu import ListCRDT, OpLog
from diamond_types_tpu.text.crdt import merge_oplogs

# Unicode-heavy alphabet mirroring the reference's fuzzer charset
# (reference: src/list_fuzzer_tools.rs:18-24 — ASCII, Latin-1, Greek,
# arrows, and ASTRAL ancient-roman symbols): exercises the UTF-32 content
# arenas, UTF-8 encode/decode columns, and the wchar (UTF-16) interop
# maps, where surrogate-pair chars occupy two wchar units.
ALPHABET = ("abcdefghijklmnop_ XYZ123*&^%$#@!~`:;'\"|\n"
            "©¥½"              # Latin-1 supplement
            "ΎΔδϠ"        # Greek
            "←↯↻⇈"        # arrows
            "\U00010190\U00010194\U00010198\U0001019a")  # astral (roman)


def random_edit(rng, oplog, agent, version, content):
    """Make one random edit on top of (version, content); returns
    (new_version, new_content)."""
    doc_len = len(content)
    insert_weight = 0.65 if doc_len < 100 else 0.45
    if doc_len == 0 or rng.random() < insert_weight:
        pos = rng.randint(0, doc_len)
        n = rng.randint(1, 4)
        s = "".join(rng.choice(ALPHABET) for _ in range(n))
        lv = oplog.add_insert_at(agent, version, pos, s)
        content = content[:pos] + s + content[pos:]
    else:
        start = rng.randint(0, doc_len - 1)
        n = min(rng.randint(1, 5), doc_len - start)
        lv = oplog.add_delete_at(agent, version, start, start + n,
                                 content[start:start + n])
        content = content[:start] + content[start + n:]
    return [lv], content


@pytest.mark.parametrize("seed", range(30))
def test_single_document_random_edits(seed):
    """Random linear edits; checkout must equal the shadow string."""
    rng = random.Random(seed)
    ol = OpLog()
    agent = ol.get_or_create_agent_id("seph")
    version, expected = [], ""
    for _ in range(60):
        version, expected = random_edit(rng, ol, agent, version, expected)
        assert ol.version == version
    assert ol.checkout_tip().snapshot() == expected


@pytest.mark.parametrize("seed", range(30))
def test_single_oplog_concurrent_branches(seed):
    """Random edits on random concurrent frontiers inside ONE oplog; the
    checkout must converge no matter the branch structure."""
    rng = random.Random(1000 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("alice", "bob", "carol")]
    # Each logical branch: (version, content)
    branches = [([], "")]
    for step in range(50):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        agent = agents[rng.randrange(3)]
        version, content = random_edit(rng, ol, agent, version, content)
        branches[bi] = (version, content)
        if rng.random() < 0.2 and len(branches) < 4:
            branches.append(branches[bi])
        if rng.random() < 0.25 and len(branches) >= 2:
            # Merge two branches via transformed ops onto a fresh checkout.
            i, j = rng.sample(range(len(branches)), 2)
            vi, vj = branches[i][0], branches[j][0]
            merged_v = ol.cg.graph.version_union(vi, vj)
            b = ol.checkout(merged_v)
            branches[i] = (merged_v, b.snapshot())
            if rng.random() < 0.5 and len(branches) > 1:
                branches.pop(j if j > i else i)
    # Final: merge everything.
    full = ol.checkout_tip()
    b2 = ol.checkout_tip()
    assert full.snapshot() == b2.snapshot()


@pytest.mark.parametrize("seed", range(20))
def test_three_peer_convergence(seed):
    """Three independent oplogs diverge and repeatedly cross-merge
    (reference: merge_fuzz, src/listmerge/fuzzer.rs:34)."""
    rng = random.Random(2000 + seed)
    docs = []
    for name in ("alice", "bob", "carol"):
        d = ListCRDT()
        d.get_or_create_agent_id(name)
        docs.append(d)

    for round_ in range(12):
        # Each peer makes a few local edits.
        for idx, d in enumerate(docs):
            for _ in range(rng.randint(1, 3)):
                v, c = random_edit(rng, d.oplog, 0, d.branch.version,
                                   d.branch.snapshot())
                # keep branch in sync by direct application
                d.branch.version = v
                d.branch.content = __import__(
                    "diamond_types_tpu.utils.rope", fromlist=["Rope"]).Rope(c)
        # Random pair sync.
        i, j = rng.sample(range(3), 2)
        a, b = docs[i], docs[j]
        merge_oplogs(a.oplog, b.oplog)
        merge_oplogs(b.oplog, a.oplog)
        a.branch.merge_tip(a.oplog)
        b.branch.merge_tip(b.oplog)
        assert a.snapshot() == b.snapshot()

    # Full sync at the end.
    for i in range(3):
        for j in range(3):
            if i != j:
                merge_oplogs(docs[i].oplog, docs[j].oplog)
    finals = [d.oplog.checkout_tip().snapshot() for d in docs]
    assert finals[0] == finals[1] == finals[2]
