"""Seeded dt-lint fixture: writer-group table lock-order violation.

Acquires the lease lock (repl.leases, 2) while already holding the
writer-group table lock (repl.writergroup, 6) — backwards against the
canonical order: the table lock is a late rung, taken under the lease
lock by the floor-raise fence hook; taking them the other way around
deadlocks against that hook.
Never imported; parsed by the lint engine only.
"""


class FixtureWriterGroups:
    def backwards(self, doc_id):
        with self.writergroups.lock:
            with self.leases.lock:
                return self._grants.get(doc_id)
