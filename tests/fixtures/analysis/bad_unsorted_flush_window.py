"""Historical-bug fixture: the pre-repair _flush_window acquisition.

Re-expresses the lock-order bug the concurrency-analyzer PR caught in
the wild: the mesh flush window grabbed its per-shard device locks in
window order, not sorted order, so two windows over the same shards
could deadlock. The repaired scheduler iterates a sorted shard list;
this fixture pins the detector that caught the original. Never
imported; parsed by the lint engine only.
"""

import contextlib


class FixtureScheduler:
    def _flush_window(self, win):
        with contextlib.ExitStack() as stack:
            for s in win.shards:
                stack.enter_context(self._device_locks[s])
            return self.dispatch(win)
