"""Seeded dt-lint fixture: doc-state mutation with no fencing check.

The class participates in lease fencing (defines `_fence`) but
`hot_write` reaches `sync_doc` without any fence token on the path —
a deposed leader keeps mutating after its lease moved. Never
imported; parsed by the lint engine only.
"""


class FixtureScheduler:
    def _fence(self, doc_id, epoch):
        return True

    def hot_write(self, doc_id, ol):
        self.banks[0].sync_doc(doc_id, ol)
