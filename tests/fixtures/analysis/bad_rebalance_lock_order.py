"""Seeded dt-lint fixture: rebalancer planning lock-order violation.

Acquires the rebalancer's planning guard (repl.rebalance, 1) while
already holding the lease lock (repl.leases, 2) — backwards against
the canonical order: migration planning reads lease state (plan ->
lease), lease code must never call back into the planner.
Never imported; parsed by the lint engine only.
"""


class FixtureRebalancer:
    def backwards(self, doc_id):
        with self.leases.lock:
            with self._rebalance_lock:
                return self._last_attempt.get(doc_id)
