"""Seeded dt-lint fixture: residency-tier lock-order violation.

Acquires the hydrator's warm-map guard (io, 25) while already holding
the oplog guard (30) — backwards against the canonical order: io is
deliberately OUTER to oplog (snapshot encode runs under the oplog
guard INSIDE an io-serialized pass, never the reverse).
Never imported; parsed by the lint engine only.
"""


class FixtureHydrator:
    def backwards(self, doc_id):
        with self.store.lock:
            with self._hydrate_lock:
                return self._warm.get(doc_id)
