"""Seeded dt-lint fixture: qos controller lock-order violation.

Acquires the adaptive-admission controller's `_qos_lock` (qos, 8)
while already holding the scheduler's global lock (10) — backwards
against the canonical order: the control loop takes qos THEN global
to read queue fills, and code on the hot admission path under the
global lock must read the published deadline table lock-free, never
the controller's own lock (that inversion is exactly the deadlock the
rung exists to forbid).
Never imported; parsed by the lint engine only.
"""


class FixtureScheduler:
    def backwards(self, shard):
        with self.lock:
            with self._qos_lock:
                return self.queue.bucket_fill(shard)
