"""Seeded dt-lint fixture: follower-read cache lock-order violation.

Acquires the checkout cache's guard (io, 25) while already holding the
oplog guard (30) — backwards against the canonical order: the cache
guard is deliberately OUTER to oplog (the single-flight leader
materializes checkouts under the oplog guard OUTSIDE the cache guard,
never the reverse).
Never imported; parsed by the lint engine only.
"""


class FixtureReadPath:
    def backwards(self, doc_id, fkey):
        with self.store.lock:
            with self._cache_lock:
                return self._entries.get((doc_id, fkey))
