"""Seeded dt-lint fixture: violations silenced by suppressions.

Same shapes as the bad_* fixtures but every finding carries a
same-line ignore[rule] comment — the file must lint clean (and every
suppression absorbs a real finding, so the stale-suppression audit
stays quiet too). Never imported; parsed by the lint engine only.
"""


class FixtureStore:
    def flush_blocking(self, buf):
        with self.lock:
            import jax
            jax.block_until_ready(buf)  # dt-lint: ignore[device-under-lock]


_fixture_jit_cache = {}


def lookup(b, n):
    key = (b, n)
    return _fixture_jit_cache.get(key)  # dt-lint: ignore[jit-cache-key]
