# dt-lint: skip-file
"""Seeded dt-lint fixture: file-level opt-out.

Contains a blatant lock-order violation that must NOT be reported
because of the skip-file marker above. Never imported; parsed by the
lint engine only.
"""


class FixtureScheduler:
    def backwards(self, s):
        with self._device_locks[s]:
            with self._shard_locks[s]:
                return s
