"""Seeded dt-lint fixture: exemplar family with no producer.

Maps a prom histogram to a TimeSeries family no producer ever writes
— the exemplar join would silently return nothing forever. Never
imported; parsed by the lint engine only.
"""

_EXEMPLAR_FAMILIES = {
    "dt_fixture_latency_seconds": "serve.bogus_family",
}
