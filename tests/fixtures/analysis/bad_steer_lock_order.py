"""Seeded dt-lint fixture: shape-steer table lock-order violation.

Acquires a per-device replay guard (device, 40) while already holding
the warm-class table guard (`_steer_lock`, leaf, 50) — backwards
against the canonical order: `snap`/`note_warm` are pure table reads
called strictly OUTSIDE the jit-cache and device locks by design, so
steering code never reaches back down to a device rung while the
table guard is held. Never imported; parsed by the lint engine only.
"""


class FixtureSteerPolicy:
    def backwards(self, cache, key):
        with self._steer_lock:
            with self._device_locks[0]:
                return self._table[cache].get(key)
