"""Seeded dt-lint fixture: host effects inside a traced body.

The jitted body reads the host clock — traced code reruns an
unpredictable number of times (trace + compile + replay). Never
imported; parsed by the lint engine only.
"""

import time

import jax


def stamped_step(x):
    t = time.time()
    print("stepping", t)
    return x + t


stamped = jax.jit(stamped_step)
