"""Seeded dt-lint fixture: metrics-schema drift.

Bumps a replication counter key that ReplicationMetrics._GROUPS does
not declare — prom zero-fill and the repl.* time-series table would
never export it. Never imported; parsed by the lint engine only.
"""


class FixtureReporter:
    def note_acquire(self):
        self.metrics.bump("leases", "acquries")
