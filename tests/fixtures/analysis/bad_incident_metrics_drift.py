"""Seeded dt-lint fixture: incident kind schema drift.

Opens an incident whose kind literal is not declared in
obs.incident.INCIDENT_KINDS — the dt_incident_opened_total{kind}
prom family zero-fills only the declared tuple, and the store would
reject the kind at runtime anyway.
Never imported; parsed by the lint engine only.
"""


class FixtureWatcher:
    def alarm(self, series):
        self.store.open_incident("rate_stalled", series, {"silent_s": 31.0})
