"""Seeded dt-lint fixture: lock-order violation.

Acquires the shard lock while already holding a device lock —
backwards against the canonical order (shard(20) < device(40)).
Never imported; parsed by the lint engine only.
"""


class FixtureScheduler:
    def backwards(self, s):
        with self._device_locks[s]:
            with self._shard_locks[s]:
                return self.banks[s]
