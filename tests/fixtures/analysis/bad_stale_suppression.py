"""Seeded dt-lint fixture: a suppression that suppresses nothing.

The ignore comment below shields a line where no finding fires any
more — left in place it would silently hide the NEXT real finding on
that line. Never imported; parsed by the lint engine only.
"""


class FixtureQuiet:
    def tidy(self):
        return len([])  # dt-lint: ignore[lock-order]
