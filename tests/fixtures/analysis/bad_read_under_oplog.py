"""Historical-bug fixture: the pre-repair read path.

Re-expresses the device-under-lock bug the concurrency-analyzer PR
caught in the wild: bank.text synced the doc to the device while
still holding the store's oplog guard, so every submit and oplog
reader stalled behind a device round-trip. The repaired bank splits
the oplog read from the device fetch; this fixture pins the detector
that caught the original. Never imported; parsed by the lint engine
only.
"""


class FixtureBank:
    def text(self, doc_id):
        with self.store.lock:
            self.sync_doc(doc_id, None)
            return self.checkout_text(doc_id)
