"""Seeded dt-lint fixture: QoS metrics-schema drift.

Bumps a per-class admission counter key that qos.metrics.
QOS_CLASS_KEYS does not declare — the dt_qos_*{class} prom families
zero-fill only the declared tuple, so the counter would never export.
Never imported; parsed by the lint engine only.
"""


class FixtureGate:
    def note_shed(self, cls):
        self.metrics.bump_class(cls, "shedded")
