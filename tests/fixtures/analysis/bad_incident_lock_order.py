"""Seeded dt-lint fixture: incident engine lock-order violation.

Acquires the oplog guard (30) while already holding the detector's
state guard (`_incident_lock`, leaf, 50) — backwards against the
canonical order: the incident locks are innermost leaves; poll()
gathers every TimeSeries/recorder read BEFORE taking the lock and
opens bundles AFTER releasing it, so nothing may nest under them.
Never imported; parsed by the lint engine only.
"""


class FixtureDetector:
    def backwards(self, series):
        with self._incident_lock:
            with self.store.lock:
                return self._state[series]
