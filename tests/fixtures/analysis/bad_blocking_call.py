"""Seeded dt-lint fixture: blocking call under a hot-path lock.

Sleeps while holding the scheduler's global lock — every submit on
every shard stalls behind the sleep. Never imported; parsed by the
lint engine only.
"""

import time


class FixtureScheduler:
    def backoff_holding_lock(self, delay_s):
        with self.lock:
            time.sleep(delay_s)
            return delay_s
