"""Seeded dt-lint fixture: unsorted multi-lock acquisition.

Acquires several device locks in a loop whose iteration source is not
lexically sorted — two threads looping over differently-ordered shard
lists deadlock. Never imported; parsed by the lint engine only.
"""

import contextlib


class FixtureScheduler:
    def grab_all(self, shards):
        with contextlib.ExitStack() as stack:
            for s in shards:
                stack.enter_context(self._device_locks[s])
            return len(shards)
