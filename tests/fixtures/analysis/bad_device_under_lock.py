"""Seeded dt-lint fixture: device dispatch under the oplog guard.

Blocks on device work while holding a Store's oplog lock — every
submit and oplog reader stalls behind the device call. Never
imported; parsed by the lint engine only.
"""


class FixtureStore:
    def flush_blocking(self, buf):
        with self.lock:
            import jax
            jax.block_until_ready(buf)
