"""Seeded dt-lint fixture: wire frame-cache lock-order violation.

Acquires the WireChannel's snapshot-frame cache guard (io, 25) while
already holding the oplog guard (30) — backwards against the canonical
order: frame builds take the oplog guard strictly OUTSIDE the cache
lock (a racing pair builds twice, caches once), never the reverse.
Never imported; parsed by the lint engine only.
"""


class FixtureWireChannel:
    def backwards(self, doc_id, key):
        with self.store.lock:
            with self._frame_cache_lock:
                return self._frames.get((doc_id, key))
