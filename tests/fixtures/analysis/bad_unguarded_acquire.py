"""Seeded dt-lint fixture: bare .acquire() with no try/finally.

Acquires a shard lock imperatively and releases it on the straight
path only — any exception in between leaves the lock held forever.
Never imported; parsed by the lint engine only.
"""


class FixtureScheduler:
    def grab_and_work(self, s):
        lk = self._shard_locks[s]
        lk.acquire()
        self.do_work(s)
        lk.release()
