"""Seeded dt-lint fixture: jit cache keyed on too few shape dims.

A 2-tuple key collides two different (batch, n_ops, max_insert)
shape classes on one compiled fn. Never imported; parsed by the lint
engine only.
"""

_fixture_jit_cache = {}


def lookup(b, n):
    key = (b, n)
    fn = _fixture_jit_cache.get(key)
    if fn is None:
        fn = object()
        _fixture_jit_cache[key] = fn
    return fn
