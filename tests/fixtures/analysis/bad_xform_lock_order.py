"""Seeded dt-lint fixture: xform jit-guard lock-order violation.

Acquires the oplog guard (30) while already holding the transform
jit-cache guard (`_xform_jit_lock`, device, 40) — backwards against
the canonical order: the device transform dispatch runs OUTSIDE the
oplog guard by design (extracts are self-contained), so planning code
releases the oplog rung before the jit guard, never re-enters under it.
Never imported; parsed by the lint engine only.
"""


class FixtureXformPlanner:
    def backwards(self, sessions):
        with self._xform_jit_lock:
            with self.store.lock:
                return [self._resolve(s) for s in sessions]
