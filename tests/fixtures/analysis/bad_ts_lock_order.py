"""Seeded dt-lint fixture: telemetry ring lock-order violation.

Acquires the oplog guard (30) while already holding the TimeSeries
ring guard (`_ts_lock`, leaf, 50) — backwards against the canonical
order: the telemetry locks are innermost leaves, taken by record_*
double-writes while serve/read/replicate locks are already held, and
nothing may be acquired under them.
Never imported; parsed by the lint engine only.
"""


class FixtureTelemetry:
    def backwards(self, name, n):
        with self._ts_lock:
            with self.store.lock:
                return self._windows[name] + n
