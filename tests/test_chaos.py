"""Partition-safety chaos suite (`pytest -m chaos`).

Acceptance for the quorum/fencing PR: seeded soaks combining
asymmetric partitions, crash-restarts, and node join+leave must end
byte-identical across live replicas, the split-brain detector (which
scans EVERY node incarnation's lease activation history for two ACTIVE
holders sharing a (doc, epoch)) must report zero violations, and a
fenced stale-owner write must be observably REJECTED (counter > 0),
not merged.

Everything is in-process on ephemeral localhost ports and sized for
the tier-1 gate: tight TTLs, few rounds, seeded fault schedules.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from diamond_types_tpu.replicate import attach_replication
from diamond_types_tpu.replicate.soak import run_replicate_soak

pytestmark = [pytest.mark.chaos, pytest.mark.replicate]


def _post(addr, path, obj, headers=None):
    req = urllib.request.Request(
        f"http://{addr}{path}",
        data=json.dumps(obj).encode("utf8") if isinstance(obj, dict)
        else obj)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, r.read()


# ---- acceptance soaks ----------------------------------------------------

def test_asym_partition_crash_churn_soak_no_split_brain(tmp_path):
    """The headline acceptance run: one-way partitions + two
    crash-restarts + a join-then-leave, seeded. Live replicas end
    byte-identical and no (doc, epoch) ever had two ACTIVE holders."""
    r = run_replicate_soak(servers=3, docs=2, rounds=8,
                           edits_per_round=2, seed=5, drop_rate=0.05,
                           partition_rounds=3, reconcile_rounds=16,
                           lease_ttl_s=0.3, crash=True, asym=True,
                           churn=True, data_dir=str(tmp_path))
    assert r["converged"], r["doc_lengths"]
    assert r["zero_split_brain"], r["split_brain"]
    assert r["crashes"] == 2
    assert r["quorum"]["rounds_won"] >= 1       # leases went through
    assert r["quorum"]["rejoins_completed"] >= 1
    assert r["config"]["asym"] and r["config"]["churn"]
    assert r["faults"]["partition_blocks"] >= 1


def test_asym_partition_soak_converges(tmp_path):
    """Asymmetric-cut-only soak at a different seed: the TTL-takeover
    killer case (a cannot reach b, b still hears a)."""
    r = run_replicate_soak(servers=3, docs=2, rounds=6,
                           edits_per_round=2, seed=11, drop_rate=0.1,
                           partition_rounds=3, reconcile_rounds=16,
                           lease_ttl_s=0.3, asym=True,
                           data_dir=str(tmp_path))
    assert r["converged"], r["doc_lengths"]
    assert r["zero_split_brain"], r["split_brain"]
    assert r["faults"]["oneway_partitions"] == [] \
        or r["config"]["asym"]   # healed by report time


# ---- targeted scenarios --------------------------------------------------

def _mesh(n, tmp_path, lease_ttl_s=5.0, serve_shards=1):
    from diamond_types_tpu.tools.server import serve
    httpds, addrs = [], []
    for i in range(n):
        httpd = serve(port=0, data_dir=str(tmp_path / f"s{i}"),
                      serve_shards=serve_shards)
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            lease_ttl_s=lease_ttl_s, backoff_base_s=0.01,
            backoff_cap_s=0.05,
            journal_prefix=str(tmp_path / f"s{i}" / "_replica")))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def _teardown(httpds):
    for h in httpds:
        h.shutdown()
        h.server_close()


def _step(nodes):
    for n in nodes:
        n.table.probe_once()
        n.maintain()


def test_fenced_stale_owner_write_rejected(tmp_path):
    """Acceptance: a proxied mutation carrying a superseded lease epoch
    is rejected with 409 (fencing.rejected_writes > 0), never merged;
    the proxier counts the fenced relay and falls back local."""
    httpds, nodes, addrs = _mesh(2, tmp_path)
    try:
        _step(nodes)
        doc = "fence-doc"
        owner = nodes[0].desired_owner(doc)
        owner_node = next(n for n in nodes if n.self_id == owner)
        other_node = next(n for n in nodes if n.self_id != owner)
        assert owner_node.owns(doc)
        epoch = owner_node.leases.get(doc).epoch
        # a successor epoch gets promised on the owner (e.g. a takeover
        # during a partition): the floor passes the old lease
        ok, _ = owner_node.leases.promise(doc, epoch + 5,
                                          other_node.self_id)
        assert ok
        # a write claiming the OLD epoch must now bounce with 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(owner, f"/doc/{doc}/edit",
                  {"agent": "stale", "pos": 0, "insert": "ghost"},
                  headers={"X-DT-Proxied": "1",
                           "X-DT-Lease-Epoch": str(epoch)})
        assert ei.value.code == 409
        body = json.loads(ei.value.read())
        assert body["error"] == "fenced"
        assert body["max_epoch"] == epoch + 5
        assert owner_node.metrics.get("fencing",
                                      "rejected_writes") == 1
        # ... and nothing was merged
        with urllib.request.urlopen(f"http://{owner}/doc/{doc}",
                                    timeout=5) as r:
            assert b"ghost" not in r.read()
        # proxier side: a relay stamped with the stale epoch (the
        # other node still believes the old lease) gets fenced and
        # falls back local
        other_node.leases.observe_remote(doc, owner, epoch, "active",
                                         ttl_s=60.0)
        relay = other_node.proxy(
            owner, f"/doc/{doc}/edit",
            json.dumps({"agent": "relay", "pos": 0,
                        "insert": "via proxy"}).encode("utf8"),
            doc_id=doc)
        assert relay is None
        assert other_node.metrics.get("proxy", "fenced_relays") == 1
        # the owner's own next admit self-revokes the stale lease
        assert not owner_node.owns(doc)
        assert owner_node.metrics.get("fencing",
                                      "stale_lease_revoked") == 1
    finally:
        _teardown(httpds)


def test_crash_restart_rejoins_and_never_reissues_epoch(tmp_path):
    """Acceptance (bugfix satellite): a crashed-and-restarted node boots
    fenced (rejoining: every admit denied), must re-earn quorum, and
    its re-acquired lease epoch is STRICTLY ABOVE anything it issued in
    its previous life — even though the old lease was never released."""
    httpds, nodes, addrs = _mesh(3, tmp_path, lease_ttl_s=0.5)
    try:
        _step(nodes)
        # find a doc owned by node 0 so the crash hits the lease holder
        doc = next(f"crash-doc-{i}" for i in range(50)
                   if nodes[0].desired_owner(f"crash-doc-{i}")
                   == addrs[0])
        assert nodes[0].owns(doc)
        old_epoch = nodes[0].leases.get(doc).epoch
        old_inc = nodes[0].membership.self_incarnation
        crashed = nodes[0]
        # crash: tear down WITHOUT journal close (the WAL replays)
        crashed.journal = None
        crashed.leases.journal = None
        httpds[0].shutdown()
        httpds[0].server_close()
        # reboot on the same port + data dir
        from diamond_types_tpu.tools.server import serve
        httpd = serve(port=int(addrs[0].split(":")[1]),
                      data_dir=str(tmp_path / "s0"), serve_shards=1)
        httpds[0] = httpd
        node = attach_replication(
            httpd, addrs[0], [addrs[1], addrs[2]], lease_ttl_s=0.5,
            backoff_base_s=0.01, backoff_cap_s=0.05,
            journal_prefix=str(tmp_path / "s0" / "_replica"))
        nodes[0] = node
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        # restored: fenced rejoining state, bumped incarnation, floor
        assert node.rejoining
        assert node.membership.self_incarnation > old_inc
        assert node.leases.max_epoch_of(doc) >= old_epoch
        assert not node.owns(doc)              # denied while rejoining
        assert node.metrics.get("fencing", "rejoin_denials") >= 1
        # probes confirm a quorum of voters -> the fence lifts
        for _ in range(4):
            _step(nodes)
            if not node.rejoining:
                break
        assert not node.rejoining
        assert node.metrics.get("quorum", "rejoins_completed") == 1
        # re-acquisition goes through quorum at a FRESH epoch
        assert node.owns(doc)
        assert node.leases.get(doc).epoch > old_epoch
        # the detector over both incarnations sees no shared epoch
        hist = (crashed.leases.activation_history()
                + node.leases.activation_history())
        seen = {}
        for rec in hist:
            key = (rec["doc"], rec["epoch"])
            assert seen.setdefault(key, rec["holder"]) == rec["holder"]
        epochs = [rec["epoch"] for rec in hist if rec["doc"] == doc]
        assert len(epochs) == len(set(epochs))
    finally:
        _teardown(httpds)


def test_membership_join_leave_moves_ownership(tmp_path):
    """Dynamic membership: a joiner enters the universe via
    /replicate/join + gossip (docs migrate to it by handoff), and an
    explicit leave deterministically migrates them back."""
    httpds, nodes, addrs = _mesh(2, tmp_path, lease_ttl_s=5.0)
    try:
        _step(nodes)
        # boot a third server and join it through node 0
        from diamond_types_tpu.tools.server import serve
        httpd3 = serve(port=0, data_dir=str(tmp_path / "s2"),
                       serve_shards=1)
        addr3 = f"127.0.0.1:{httpd3.server_address[1]}"
        node3 = attach_replication(
            httpd3, addr3, [], lease_ttl_s=5.0, backoff_base_s=0.01,
            backoff_cap_s=0.05,
            journal_prefix=str(tmp_path / "s2" / "_replica"))
        threading.Thread(target=httpd3.serve_forever,
                         daemon=True).start()
        assert node3.join_mesh(addrs[0])
        all_nodes = nodes + [node3]
        _step(all_nodes)        # gossip spreads the join
        for n in all_nodes:
            assert n.membership.universe() == sorted(addrs + [addr3])
            assert n.membership.quorum_size() == 2
        # ownership is computed over the grown universe on every node
        doc = next(f"churn-doc-{i}" for i in range(100)
                   if node3.desired_owner(f"churn-doc-{i}") == addr3)
        assert nodes[0].desired_owner(doc) == addr3
        assert node3.owns(doc)
        epoch_joined = node3.leases.get(doc).epoch
        # explicit leave (announced to node 0; gossip spreads LEFT)
        _post(addrs[0], "/replicate/leave", {"id": addr3})
        httpd3.shutdown()
        httpd3.server_close()
        _step(nodes)
        for n in nodes:
            assert addr3 not in n.membership.universe()
            assert addr3 not in n.membership.voters()
            assert n.membership.quorum_size() == 2
        # the doc deterministically re-homes among the survivors, at a
        # fenced (higher) epoch once the old lease expires
        new_owner = nodes[0].desired_owner(doc)
        assert new_owner in addrs
        owner_node = next(n for n in nodes if n.self_id == new_owner)
        owner_node.leases.observe_remote(doc, addr3, epoch_joined,
                                         "active", ttl_s=0.0)
        assert owner_node.owns(doc)
        assert owner_node.leases.get(doc).epoch > epoch_joined
    finally:
        _teardown(httpds)
