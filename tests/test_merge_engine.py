"""Merge engine unit tests: hand-built concurrent scenarios.

Mirrors the style of the reference's inline tests (reference:
src/listmerge/merge.rs tests, src/listmerge/simple_oplog.rs).
"""

from diamond_types_tpu import ListCRDT, OpLog
from diamond_types_tpu.text.crdt import merge_oplogs


def make_simple(agent_name="a"):
    doc = ListCRDT()
    doc.get_or_create_agent_id(agent_name)
    return doc


def test_linear_insert_delete():
    doc = make_simple()
    doc.insert(0, 0, "hello world")
    doc.delete(0, 5, 11)
    doc.insert(0, 5, "!")
    assert doc.snapshot() == "hello!"

    # Replay from scratch via checkout.
    b = doc.oplog.checkout_tip()
    assert b.snapshot() == "hello!"


def test_concurrent_inserts_two_agents():
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "aaa")
    # bob inserts concurrently at the same place
    ol.add_insert_at(b, [], 0, "bbb")
    br = ol.checkout_tip()
    # Deterministic agent-name ordering: alice's run sorts before bob's.
    assert br.snapshot() == "aaabbb"


def test_concurrent_inserts_interleave_stability():
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "Hi ")
    v1 = ol.version
    ol.add_insert_at(a, v1, 3, "alice")
    ol.add_insert_at(b, v1, 3, "bob")
    s = ol.checkout_tip().snapshot()
    assert s == "Hi alicebob"


def test_concurrent_delete_same_region():
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "abcdef")
    v = ol.version
    ol.add_delete_at(a, v, 1, 4)       # -> aef
    ol.add_delete_at(b, v, 2, 5)       # -> abf
    s = ol.checkout_tip().snapshot()
    assert s == "af"


def test_insert_inside_concurrently_deleted():
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "abcd")
    v = ol.version
    ol.add_delete_at(a, v, 0, 4)        # alice deletes everything
    ol.add_insert_at(b, v, 2, "XY")     # bob inserts in the middle
    s = ol.checkout_tip().snapshot()
    assert s == "XY"


def test_backspace_run():
    ol = OpLog()
    a = ol.get_or_create_agent_id("a")
    ol.add_insert_at(a, [], 0, "abc")
    # Backspace 3 times from the end: deletes 2, then 1, then 0.
    v = ol.version
    v = [ol.add_delete_at(a, v, 2, 3)]
    v = [ol.add_delete_at(a, v, 1, 2)]
    v = [ol.add_delete_at(a, v, 0, 1)]
    assert ol.checkout_tip().snapshot() == ""
    # The three deletes should have merged into one reverse run.
    del_runs = [r for r in ol.ops.runs if r.kind == 1]
    assert len(del_runs) == 1 and not del_runs[0].fwd


def test_merge_branch_incremental():
    doc = make_simple()
    doc.insert(0, 0, "hello")
    b = doc.oplog.checkout_tip()
    doc.insert(0, 5, " world")
    assert b.snapshot() == "hello"
    b.merge(doc.oplog, doc.oplog.version)
    assert b.snapshot() == "hello world"


def test_merge_oplogs_convergence():
    d1 = make_simple("alice")
    d2 = ListCRDT()
    d2.get_or_create_agent_id("bob")

    d1.insert(0, 0, "base ")
    merge_oplogs(d2.oplog, d1.oplog)
    d2.branch.merge_tip(d2.oplog)
    assert d2.snapshot() == "base "

    d1.insert(0, 5, "from-alice")
    d2.insert(0, 5, "from-bob")

    merge_oplogs(d1.oplog, d2.oplog)
    merge_oplogs(d2.oplog, d1.oplog)
    s1 = d1.oplog.checkout_tip().snapshot()
    s2 = d2.oplog.checkout_tip().snapshot()
    assert s1 == s2
    assert "from-alice" in s1 and "from-bob" in s1


def test_double_delete_merge():
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "xyz")
    v = ol.version
    ol.add_delete_at(a, v, 1, 2)
    ol.add_delete_at(b, v, 1, 2)
    assert ol.checkout_tip().snapshot() == "xz"
