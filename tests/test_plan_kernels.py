"""Device fork/join plan execution: tape replay, batched time travel, and
batched origin queries — validated against the M1 engine and the host dense
executor (the reference's own differential pattern, test_conversion.rs)."""

import numpy as np
import pytest

from diamond_types_tpu.core.span import UNDERWATER_START
from diamond_types_tpu.listmerge.dense import INSERTED, NIY
from diamond_types_tpu.text.op import INS
from diamond_types_tpu.tpu.plan_kernels import (entry_frontier,
                                                origin_query_jax,
                                                snapshot_rows,
                                                texts_at_versions)
from tests.test_encode import build_random_oplog
from tests.test_linearize import _fuzz_oplog


def _doc_len_arrays(oplog, plan, tape):
    """(len_ord, plen): per-slot char lengths in document order, underwater
    clipped to the real base text (mirrors texts_at_versions)."""
    base_text = oplog.checkout(plan.common).snapshot()
    plen = len(base_text)
    sid, slen = tape.sorted_ids, tape.sorted_lens
    uw = sid >= UNDERWATER_START
    uw_off = np.where(uw, sid - UNDERWATER_START, 0)
    text_len = np.where(
        uw, np.maximum(0, np.minimum(uw_off + slen, plen) - uw_off),
        slen).astype(np.int64)
    return text_len[tape.perm], plen


@pytest.mark.parametrize("seed", range(6))
def test_device_rows_give_correct_historical_texts(seed):
    """Every snapshot row, materialized, must equal the M1 engine's
    checkout at that entry's version frontier."""
    ol = build_random_oplog(seed, steps=40)
    plan, ex, tape, rows = snapshot_rows(ol, [])
    if not plan.entries:
        pytest.skip("linear history: no conflict zone")
    texts = texts_at_versions(ol, range(len(plan.entries)))
    for k in range(len(plan.entries)):
        f = entry_frontier(ol.cg.graph, plan, k)
        expected = ol.checkout(f).snapshot()
        assert texts[k] == expected, f"entry {k} at {f}"


@pytest.mark.parametrize("seed", range(4))
def test_device_time_travel_cross_sync(seed):
    ol = _fuzz_oplog(seed, steps=25, cross_sync=True)
    plan, ex, tape, rows = snapshot_rows(ol, [])
    ks = list(range(0, len(plan.entries), 3))
    texts = texts_at_versions(ol, ks)
    for i, k in enumerate(ks):
        f = entry_frontier(ol.cg.graph, plan, k)
        assert texts[i] == ol.checkout(f).snapshot()


@pytest.mark.parametrize("seed", range(6))
def test_device_origin_queries_match_tracker(seed):
    """For every entry whose first op is an insert and which has at most
    one in-zone parent, the device origin query against the parent-version
    row must reproduce the (origin_left, origin_right) pair the host
    tracker extracted during the real merge. (Later ops of an entry fold in
    intra-branch effects — that sequential threading stays on the host/C++
    tier by design.)"""
    import jax.numpy as jnp

    ol = _fuzz_oplog(100 + seed, steps=25, cross_sync=True)
    plan, ex, tape, rows = snapshot_rows(ol, [])
    if not plan.entries:
        pytest.skip("no conflict zone")
    len_ord, _plen = _doc_len_arrays(ol, plan, tape)
    ids_ord = tape.sorted_ids[tape.perm]

    base_row_sorted = tape.is_base.astype(np.uint8)
    checked = 0
    for k, en in enumerate(plan.entries):
        if len(en.parents) > 1:
            continue
        first = next(ol.ops.iter_range(en.span))
        if first.kind != INS:
            continue
        row_sorted = rows[en.parents[0]] if en.parents else base_row_sorted
        row_ord = row_sorted[tape.perm]
        ol_j, ol_off, orr_j, orr_off = (
            np.asarray(x) for x in origin_query_jax(
                jnp.asarray(row_ord.astype(np.int32)),
                jnp.asarray(len_ord.astype(np.int32)),
                jnp.asarray(np.array([first.start], dtype=np.int32))))
        got_ol = -1 if ol_j[0] < 0 else int(ids_ord[ol_j[0]] + ol_off[0])
        got_orr = -1 if orr_j[0] < 0 else int(ids_ord[orr_j[0]] + orr_off[0])

        slot = ex.slots[ex._ins_lookup(first.lv)]
        assert slot.ids == first.lv
        assert got_ol == slot.ol, (k, first.lv, got_ol, slot.ol)
        assert got_orr == slot.orr, (k, first.lv, got_orr, slot.orr)
        checked += 1
    assert checked >= 3, "fuzz produced too few first-op inserts"


def test_wide_fanin_origins_batched():
    """The north-star shape: N replicas concurrently editing one base doc.
    ALL their first-insert origins resolve in ONE device call against the
    shared base row."""
    import jax.numpy as jnp

    from diamond_types_tpu.text.oplog import OpLog

    ol = OpLog()
    base_agent = ol.get_or_create_agent_id("base")
    v = []
    text = "abcdefghijklmnopqrstuvwxyz" * 4
    lv = ol.add_insert_at(base_agent, v, 0, text)
    base_v = [lv]
    n_rep = 48
    rng = np.random.RandomState(7)
    pos = rng.randint(0, len(text) + 1, size=n_rep)
    first_lvs = []
    for i in range(n_rep):
        ag = ol.get_or_create_agent_id(f"rep{i:03d}")
        first_lvs.append(ol.add_insert_at(ag, base_v, int(pos[i]),
                                          f"<{i}>"))

    plan, ex, tape, rows = snapshot_rows(ol, [])
    len_ord, _ = _doc_len_arrays(ol, plan, tape)
    ids_ord = tape.sorted_ids[tape.perm]
    row_ord = tape.is_base.astype(np.uint8)[tape.perm]

    ol_j, ol_off, orr_j, orr_off = (
        np.asarray(x) for x in origin_query_jax(
            jnp.asarray(row_ord.astype(np.int32)),
            jnp.asarray(len_ord.astype(np.int32)),
            jnp.asarray(pos.astype(np.int32))))

    for i in range(n_rep):
        slot = ex.slots[ex._ins_lookup(first_lvs[i])]
        got_ol = -1 if ol_j[i] < 0 else int(ids_ord[ol_j[i]] + ol_off[i])
        got_orr = -1 if orr_j[i] < 0 else int(ids_ord[orr_j[i]] + orr_off[i])
        assert got_ol == slot.ol and got_orr == slot.orr, i


def test_tape_state_lattice_respected():
    """Device rows only ever contain lattice values 0/1/2 and base slots
    start Inserted in fresh rows."""
    ol = build_random_oplog(3, steps=40)
    plan, ex, tape, rows = snapshot_rows(ol, [])
    assert rows.max() <= 2
    assert set(np.unique(rows)) <= {NIY, INSERTED, 2}


@pytest.mark.parametrize("seed", range(4))
def test_native_tape_source_time_travel(seed):
    """The C++-engine-backed tape source (no Python zone execution) must
    produce the same historical texts as the Python-executor source and
    the M1 checkout oracle."""
    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    ol = _fuzz_oplog(300 + seed, steps=25, cross_sync=True)
    plan, src, tape, rows = snapshot_rows(ol, [], entries=[],
                                          source="native")
    if not plan.entries:
        pytest.skip("no conflict zone")
    ks = list(range(0, len(plan.entries), 2))
    texts_native = texts_at_versions(ol, ks, source="native")
    texts_python = texts_at_versions(ol, ks, source="python")
    assert texts_native == texts_python
    for i, k in enumerate(ks):
        f = entry_frontier(ol.cg.graph, plan, k)
        assert texts_native[i] == ol.checkout(f).snapshot(), k


def test_native_tape_source_incremental():
    from diamond_types_tpu.native import native_available
    if not native_available():
        pytest.skip("native core not built")
    ol = _fuzz_oplog(77, steps=25, cross_sync=True)
    mid = ol.cg.graph.find_dominators([len(ol) // 2])
    plan, src, tape, rows = snapshot_rows(ol, mid, entries=[],
                                          source="native")
    if not plan.entries:
        pytest.skip("no conflict zone")
    ks = [0, len(plan.entries) - 1]
    tn = texts_at_versions(ol, ks, from_frontier=mid, source="native")
    tp = texts_at_versions(ol, ks, from_frontier=mid, source="python")
    assert tn == tp
