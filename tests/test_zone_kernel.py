"""Device zone kernel (tpu/zone_kernel.py) — differential tests against
the NumPy reference executor and the tracker engines. Runs on the CPU
backend (conftest pins JAX_PLATFORMS=cpu for tests); the same jitted scan
is what the bench executes on the chip.
"""

import os
import random

import numpy as np
import pytest

from diamond_types_tpu import OpLog
from diamond_types_tpu.tpu.zone_kernel import (pack_zone_tape,
                                               zone_checkout_device)
from diamond_types_tpu.listmerge.zone_np import prepare_zone

from conftest import reference_path
from test_zone import random_edit

BENCH_DATA = reference_path("benchmark_data")


@pytest.mark.parametrize("seed", range(25))
def test_zone_kernel_fuzz(seed):
    """Random concurrent branches; the device scan must match the tracker
    checkout byte for byte."""
    rng = random.Random(5300 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("alice", "bob", "git")]
    branches = [([], "")]
    for _ in range(40):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        # same-agent-on-parallel-branches included (agent picked freely)
        agent = agents[rng.randrange(len(agents))]
        version, content = random_edit(rng, ol, agent, version, content)
        if rng.random() < 0.3 and len(branches) < 5:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    txt, fr = zone_checkout_device(ol)
    b = ol.checkout_tip()
    assert txt == b.snapshot()
    assert sorted(fr) == sorted(b.version)


@pytest.mark.parametrize("seed", range(8))
def test_zone_kernel_tiny_budgets(seed):
    """Force sub-step splitting (continuation blocks, delete spill) with
    tiny budgets; the packing must not change the result."""
    rng = random.Random(6400 + seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("a", "b")]
    branches = [([], "")]
    for _ in range(30):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        version, content = random_edit(rng, ol, agents[rng.randrange(2)],
                                       version, content)
        if rng.random() < 0.35 and len(branches) < 4:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    prep = prepare_zone(ol)
    if not prep.plan.entries:
        return
    tape = pack_zone_tape(prep, max_blocks=2, max_chars=4, max_dels=1)
    txt, _ = zone_checkout_device(ol, prep=prep, tape=tape)
    assert txt == ol.checkout_tip().snapshot()


def test_zone_kernel_friendsforever():
    """Real-corpus parity through the jitted scan (two-agent realtime
    trace; the other corpora run under DT_ZONE_KERNEL_BIG=1 — minutes on
    the CPU backend — and in the bench on the chip)."""
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(os.path.join(BENCH_DATA, "friendsforever.dt"), "rb") as f:
        ol = load_oplog(f.read())
    txt, fr = zone_checkout_device(ol)
    b = ol.checkout_tip()
    assert txt == b.snapshot()
    assert sorted(fr) == sorted(b.version)


@pytest.mark.parametrize("corpus", ["git-makefile.dt", "node_nodecc.dt"])
def test_zone_kernel_big_corpora(corpus):
    """Big-corpus parity through the jitted scan IN DEFAULT CI (VERDICT
    r3: the old skip's premise — "bench covers it on the chip" — was
    false whenever the accelerator tunnel wedged, which was most of
    rounds 2-3; minutes of CPU-backend scan beat zero coverage)."""
    from diamond_types_tpu.encoding.decode import load_oplog
    with open(os.path.join(BENCH_DATA, corpus), "rb") as f:
        ol = load_oplog(f.read())
    txt, _ = zone_checkout_device(ol)
    assert txt == ol.checkout_tip().snapshot()


def test_zone_engine_behind_branch_merge(monkeypatch):
    """DT_TPU_ZONE=1 selects the zone engine behind the same
    Branch.merge boundary as every other engine."""
    import random
    from diamond_types_tpu import OpLog
    rng = random.Random(99)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("za", "zb")]
    branches = [([], "")]
    for _ in range(30):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        version, content = random_edit(rng, ol, agents[rng.randrange(2)],
                                       version, content)
        if rng.random() < 0.3 and len(branches) < 4:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    expected = ol.checkout_tip().snapshot()
    monkeypatch.setenv("DT_TPU_ZONE", "1")
    b = ol.checkout_tip()
    assert b.snapshot() == expected
    assert sorted(b.version) == sorted(ol.version)


def test_batched_pack_columns_match_per_entry():
    """pack_zone_tape's whole-corpus batched column pass must produce a
    byte-identical tape to the per-entry entry_columns path it
    short-cuts (git-makefile crosses the >=200-entry batching gate)."""
    import numpy as np
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.listmerge.zone_np import prepare_zone
    from diamond_types_tpu.tpu import zone_kernel as zk
    with open(os.path.join(BENCH_DATA, "git-makefile.dt"), "rb") as f:
        ol = load_oplog(f.read())
    prep = prepare_zone(ol, [], list(ol.version))
    assert len(prep.composed) >= 200   # the gate must actually engage
    tape = zk.pack_zone_tape(prep)
    orig = zk._batched_columns
    zk._batched_columns = lambda p: {}
    try:
        tape2 = zk.pack_zone_tape(prep)
    finally:
        zk._batched_columns = orig
    for f in ("op", "arg_a", "arg_b", "snap_flag", "blk_cursor",
              "blk_prev", "blk_root", "blk_start", "blk_len", "ch_slot",
              "ch_ol_static", "ch_ol_coord", "ch_orr_own", "ch_blk",
              "ch_agent", "ch_seq", "del_kind", "del_a", "del_b"):
        assert np.array_equal(getattr(tape, f), getattr(tape2, f)), f


@pytest.mark.parametrize("slice_steps", [7, 64, 1 << 20])
def test_sliced_executor_matches_whole_tape(slice_steps):
    """execute_zone_batch_sliced_jax (bounded-length dispatches for the
    tunneled runtime that kills minutes-long programs, 2026-07-31) is
    bit-identical to the whole-tape scan — uneven slice boundaries,
    slice == 1 step short of a block, and slice > tape all covered."""
    import numpy as np
    from diamond_types_tpu.listmerge.zone_np import prepare_zone
    from diamond_types_tpu.tpu.zone_kernel import (
        execute_zone_batch_jax, execute_zone_batch_sliced_jax,
        pack_zone_tape, slice_tape_xs)

    rng = random.Random(7100)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("alice", "bob")]
    branches = [([], "")]
    for _ in range(60):
        bi = rng.randrange(len(branches))
        version, content = branches[bi]
        agent = agents[rng.randrange(len(agents))]
        version, content = random_edit(rng, ol, agent, version, content)
        if rng.random() < 0.3 and len(branches) < 4:
            branches.append((version, content))
        else:
            branches[bi] = (version, content)
    prep = prepare_zone(ol)
    if not prep.plan.entries:
        pytest.skip("degenerate zone")
    tape = pack_zone_tape(prep)
    r1, e1 = execute_zone_batch_jax(tape, prep.agent_k, prep.seq_k, 2)
    r2, e2 = execute_zone_batch_sliced_jax(
        tape, prep.agent_k, prep.seq_k, 2, slice_steps=slice_steps)
    assert np.array_equal(np.asarray(r1), np.asarray(r2))
    assert np.array_equal(np.asarray(e1), np.asarray(e2))
    # prebuilt-slices path (what the bench snippet times) agrees too
    _, xs = slice_tape_xs(tape, slice_steps)
    r3, e3 = execute_zone_batch_sliced_jax(
        tape, prep.agent_k, prep.seq_k, 2, xs_slices=xs)
    assert np.array_equal(np.asarray(r1), np.asarray(r3))
    assert np.array_equal(np.asarray(e1), np.asarray(e3))


def test_auto_slice_steps_bounds_dispatch_units():
    """auto_slice_steps keeps scan_steps x batch x W inside the
    per-dispatch device-time budget of the tunneled v5e runtime (which
    kills any single program past ~60 s — root-caused 2026-07-31), with
    a floor that keeps tiny slices from exploding dispatch counts."""
    from types import SimpleNamespace
    from diamond_types_tpu.tpu.zone_kernel import (auto_slice_steps,
                                                   _SLICE_BUDGET_UNITS)

    t = SimpleNamespace(W=23719)
    s = auto_slice_steps(t, 8)
    assert 256 <= s <= 32768
    assert s * 8 * t.W <= _SLICE_BUDGET_UNITS
    # batch growth shrinks the slice
    assert auto_slice_steps(t, 8) <= auto_slice_steps(t, 1)
    # width growth shrinks the slice
    assert auto_slice_steps(SimpleNamespace(W=400_000), 8) <= s
    # the budget takes precedence over the floor: flagship width at
    # batch 8 (git-makefile W ~560k — a 256-step dispatch there
    # measured ~35 s, inside 2x of the runtime's ~60 s kill bound)
    # must land near the budget, not on a floor clamp above it
    s_gm = auto_slice_steps(SimpleNamespace(W=560_000), 8)
    assert s_gm * 8 * 560_000 <= _SLICE_BUDGET_UNITS
    assert s_gm >= 64
    # giant widths clamp at the floor instead of going to zero
    assert auto_slice_steps(SimpleNamespace(W=10**9), 64) == 64
    # tiny zones clamp at the whole-tape-friendly ceiling
    assert auto_slice_steps(SimpleNamespace(W=1), 1) == 32768
