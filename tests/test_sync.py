"""Peer sync via version summaries + binary patches (reference: SURVEY.md
§3.5 and src/causalgraph/summary.rs)."""

import random

import pytest

from diamond_types_tpu.causalgraph.summary import (intersect_with_flat_summary,
                                                   intersect_with_summary,
                                                   summarize_versions,
                                                   summarize_versions_flat)
from diamond_types_tpu.encoding.decode import decode_into, load_oplog
from diamond_types_tpu.encoding.encode import ENCODE_FULL, ENCODE_PATCH, encode_oplog
from tests.test_encode import build_random_oplog, semantic_eq
from tests.test_fuzz import random_edit


def test_summary_roundtrip_shape():
    ol = build_random_oplog(3, steps=25)
    vs = summarize_versions(ol.cg)
    assert set(vs) <= {"alice", "bob"}
    for ranges in vs.values():
        for a, b in ranges:
            assert a < b
    common, rem = intersect_with_summary(ol.cg, vs)
    assert rem is None
    assert common == ol.version


def test_summary_intersection_disjoint_agent():
    ol = build_random_oplog(1, steps=10)
    vs = {"zelda": [[0, 5]]}
    common, rem = intersect_with_summary(ol.cg, vs)
    assert common == []
    assert rem == {"zelda": [[0, 5]]}


@pytest.mark.parametrize("seed", range(8))
def test_full_sync_via_summary_and_patch(seed):
    """The real protocol: B sends its summary, A computes the common version
    and replies with a patch from there; B ingests it."""
    rng = random.Random(seed)
    a = build_random_oplog(seed, steps=30)
    b = load_oplog(encode_oplog(a, ENCODE_FULL))

    # A advances.
    v, c = a.version, a.checkout_tip().snapshot()
    for _ in range(12):
        v, c = random_edit(rng, a, 0, v, c)

    # Handshake: B -> A summary; A -> B patch since the common version.
    vs = summarize_versions(b.cg)
    common, remainder = intersect_with_summary(a.cg, vs)
    assert remainder is None  # B has nothing A lacks
    patch = encode_oplog(a, ENCODE_PATCH, from_version=common)
    decode_into(b, patch)
    assert semantic_eq(a, b)

    # Flat summaries agree on the intersection for linear agents.
    common2, _ = intersect_with_flat_summary(a.cg, summarize_versions_flat(b.cg))
    assert a.cg.graph.frontier_contains_frontier(a.version, common2)


def test_bidirectional_sync():
    rng = random.Random(42)
    a = build_random_oplog(100, steps=20)
    b = load_oplog(encode_oplog(a, ENCODE_FULL))
    # Both diverge.
    a_alice = a.get_or_create_agent_id("alice")
    b_bob = b.get_or_create_agent_id("bob")
    va, ca = a.version, a.checkout_tip().snapshot()
    vb, cb = b.version, b.checkout_tip().snapshot()
    for _ in range(8):
        va, ca = random_edit(rng, a, a_alice, va, ca)
        vb, cb = random_edit(rng, b, b_bob, vb, cb)

    # A -> B
    common_ab, rem = intersect_with_summary(a.cg, summarize_versions(b.cg))
    assert rem is not None  # B has ops A lacks
    decode_into(b, encode_oplog(a, ENCODE_PATCH, from_version=common_ab))
    # B -> A
    common_ba, _ = intersect_with_summary(b.cg, summarize_versions(a.cg))
    decode_into(a, encode_oplog(b, ENCODE_PATCH, from_version=common_ba))
    assert semantic_eq(a, b)
