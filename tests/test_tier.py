"""Tiered doc residency: crash-safe snapshot store + hydration.

Covers the tiered_residency PR top to bottom:
  * crash-mid-compaction recovery at EVERY fsync point for both
    durable formats (PagedDocFile's 3-step tmp/replace/dirsync swap,
    DocFile's baseline-then-WAL-reset ordering) — old-or-new content,
    never torn, no stale rewrite left behind, still appendable;
  * TieredStore: per-doc compaction policy, typed DocQuarantined
    containment (one rotten home never poisons a neighbor's load);
  * Hydrator: timeout -> jittered retry -> success, sync-resolve
    exhaustion quarantine, flush-gate classification (warm keeps,
    quarantined drops, cold defers), defer-budget give-up;
  * eviction-to-snapshot parity: randomized churn through an
    undersized warm tier byte-compares against an always-resident
    control oplog (the eviction path must never drop an appended op);
  * SessionBank eviction: pending-op count + snapshot routing in the
    flight-recorder event;
  * ServeMetrics v7: hydration counter block + cold-start histogram,
    prom rendering of the dt_serve_hydration_* families;
  * the storage soak (storage/soak.py) as a small seeded smoke with
    every fault class on.
"""

import os
import random
import time
from types import SimpleNamespace

import pytest

from diamond_types_tpu.serve.hydrate import Hydrator
from diamond_types_tpu.serve.metrics import HYDRATION_KEYS, ServeMetrics
from diamond_types_tpu.storage.pages import PagedDocFile
from diamond_types_tpu.storage.store import DocFile
from diamond_types_tpu.storage.tier import (DocQuarantined, StorageFaults,
                                            TieredStore)
from diamond_types_tpu.text.oplog import OpLog

pytestmark = pytest.mark.storage


class _Boom(Exception):
    pass


def _crash_at(point):
    def hook(p):
        if p == point:
            raise _Boom(p)
    return hook


def _mk_oplog(text_parts, agent="a"):
    ol = OpLog()
    a = ol.get_or_create_agent_id(agent)
    pos = 0
    for part in text_parts:
        ol.add_insert(a, pos, part)
        pos += len(part)
    return ol


# ---- crash-mid-compaction (satellite 1) ----------------------------------

@pytest.mark.parametrize("point",
                         ["snapshot_written", "replaced", "dir_synced"])
def test_paged_compact_crash_recovers_old_or_new(tmp_path, point):
    path = str(tmp_path / "doc.pages")
    f = PagedDocFile(path)
    f.append_from(_mk_oplog(["hello ", "world ", "again "]))
    want = f.oplog.checkout_tip().snapshot()
    with pytest.raises(_Boom):
        f.compact(_crash=_crash_at(point))
    f.close()
    # never a torn mix, never a stale rewrite left to be appended onto
    assert not os.path.exists(path + ".compact")
    g = PagedDocFile(path)
    assert g.oplog.checkout_tip().snapshot() == want
    # the recovered file is a working home, not a read-only husk
    more = _mk_oplog(["hello ", "world ", "again ", "post-crash"])
    g.append_from(more)
    g.close()
    h = PagedDocFile(path)
    assert h.oplog.checkout_tip().snapshot() \
        == more.checkout_tip().snapshot()
    h.close()


@pytest.mark.parametrize("point", ["baseline_written", "wal_reset"])
def test_docfile_compact_crash_recovers(tmp_path, point):
    path = str(tmp_path / "doc.dt")
    f = DocFile(path)
    f.append_from(_mk_oplog(["alpha ", "beta "]))
    want = f.oplog.checkout_tip().snapshot()
    with pytest.raises(_Boom):
        f.compact(_crash=_crash_at(point))
    f.close()
    # a crash between baseline write and WAL reset replays the stale
    # WAL onto the new baseline; idempotent decode dedups it
    g = DocFile(path)
    assert g.oplog.checkout_tip().snapshot() == want
    g.close()


def test_stale_compact_rewrite_is_removed_on_open(tmp_path):
    path = str(tmp_path / "doc.pages")
    f = PagedDocFile(path)
    f.append_from(_mk_oplog(["content"]))
    f.close()
    with open(path + ".compact", "wb") as s:
        s.write(b"half-built rewrite from a dead process")
    g = PagedDocFile(path)
    assert not os.path.exists(path + ".compact")
    assert g.oplog.checkout_tip().snapshot() == "content"
    g.close()


# ---- TieredStore ---------------------------------------------------------

def test_tier_roundtrip_and_compaction_policy(tmp_path):
    store = TieredStore(str(tmp_path), compact_patch_records=3)
    ol = OpLog()
    a = ol.get_or_create_agent_id("w")
    for i in range(5):
        ol.add_insert(a, 0, f"r{i}.")
        store.save("d", ol)
    got = store.load("d")
    assert got is not ol       # a FRESH oplog the caller owns
    assert got.checkout_tip().snapshot() \
        == ol.checkout_tip().snapshot()
    c = store.counters()
    assert c["saves"] == 5 and c["compactions"] >= 1
    # a doc that never existed hydrates as a brand-new empty oplog
    assert len(store.load("never-saved")) == 0
    assert store.counters()["fresh_docs"] == 1


def test_tier_quarantine_is_per_doc(tmp_path):
    store = TieredStore(str(tmp_path))
    for d in ("good", "bad"):
        ol = OpLog()
        ol.add_insert(ol.get_or_create_agent_id("w"), 0, f"{d} text")
        store.save(d, ol)
    with open(store.path("bad"), "r+b") as f:
        f.write(b"\xff" * os.path.getsize(store.path("bad")))
    with pytest.raises(DocQuarantined) as ei:
        store.load("bad")
    assert ei.value.doc_id == "bad"
    assert store.is_quarantined("bad") is not None
    # sticky: the second load rejects without touching the disk again
    with pytest.raises(DocQuarantined):
        store.load("bad")
    # containment: the neighbor is untouched
    assert store.load("good").checkout_tip().snapshot() == "good text"
    c = store.counters()
    assert c["quarantines"] == 1 and c["quarantined_docs"] == 1


# ---- Hydrator ------------------------------------------------------------

class _SlowNTimes(StorageFaults):
    """Delay larger than the attempt timeout for the first `n` loads,
    then a healthy disk — the timeout->retry->success ladder."""

    def __init__(self, n, slow_s=5.0):
        super().__init__(seed=0, slow_rate=0.0)
        self._left = n
        self._slow = slow_s

    def load_delay(self, doc_id):
        if self._left > 0:
            self._left -= 1
            return self._slow
        return 0.0


def _mk_store_with_doc(tmp_path, doc="d", text="persisted", **kw):
    store = TieredStore(str(tmp_path), **kw)
    ol = OpLog()
    ol.add_insert(ol.get_or_create_agent_id("w"), 0, text)
    store.save(doc, ol)
    return store


def test_hydration_timeout_then_retry_succeeds(tmp_path):
    store = _mk_store_with_doc(tmp_path)
    store.faults = _SlowNTimes(2, slow_s=5.0)
    hyd = Hydrator(store, workers=1, attempt_timeout_s=0.02,
                   max_attempts=4, sync_wait_s=5.0)
    try:
        ol = hyd.resolve("d")
        assert ol.checkout_tip().snapshot() == "persisted"
        c = hyd.counters_snapshot()
        assert c["timeouts"] == 2 and c["retries"] >= 2
        assert c["hydrations"] == 1 and c["quarantined"] == 0
        assert hyd.cold_start.count == 1
        assert hyd.status("d") == "warm"
    finally:
        hyd.stop(checkpoint=False)


def test_sync_resolve_exhaustion_quarantines(tmp_path):
    store = _mk_store_with_doc(tmp_path)
    store.faults = _SlowNTimes(100, slow_s=5.0)   # never recovers
    hyd = Hydrator(store, workers=1, attempt_timeout_s=0.01,
                   max_attempts=2, sync_wait_s=0.05)
    try:
        with pytest.raises(DocQuarantined) as ei:
            hyd.resolve("d")
        assert ei.value.reason == "hydration_timeout"
        assert hyd.status("d") == "quarantined"
        assert hyd.counters_snapshot()["quarantined"] == 1
    finally:
        hyd.stop(checkpoint=False)


def test_flush_gate_classifies_warm_quarantined_cold(tmp_path):
    store = TieredStore(str(tmp_path))
    for d in ("warm", "cold", "bad"):
        ol = OpLog()
        ol.add_insert(ol.get_or_create_agent_id("w"), 0, d)
        store.save(d, ol)
    store.quarantine("bad", "seeded")
    # keep "cold" cold: every async attempt overruns its budget
    store.faults = _SlowNTimes(100, slow_s=5.0)
    hyd = Hydrator(store, workers=1, attempt_timeout_s=0.01,
                   max_attempts=1, gate_wait_s=0.001,
                   defer_budget_s=10.0)
    try:
        store.faults = None
        assert hyd.resolve("warm") is not None
        store.faults = _SlowNTimes(100, slow_s=5.0)
        items = [SimpleNamespace(doc_id=d, n_ops=1, epoch=-1, trace=None)
                 for d in ("warm", "cold", "bad")]
        keep, defer, dropped = hyd.flush_gate(0, items)
        assert [i.doc_id for i in keep] == ["warm"]
        assert [i.doc_id for i in defer] == ["cold"]
        assert [i.doc_id for i in dropped] == ["bad"]
        c = hyd.counters_snapshot()
        assert c["quarantined_drops"] == 1 and c["deferrals"] == 1
    finally:
        hyd.stop(checkpoint=False)


def test_second_gate_visit_escalates_to_sync_hydration(tmp_path):
    # async hydration never lands (worker loads overrun the attempt
    # budget) but the SYNC path recovers: the first gate visit defers,
    # the second hydrates in-flush instead of livelocking the drain
    import threading

    class _SlowWorkersOnly(StorageFaults):
        def __init__(self):
            super().__init__(seed=0, slow_rate=0.0)

        def load_delay(self, doc_id):
            t = threading.current_thread().name
            return 5.0 if t.startswith("hydrate-worker") else 0.0

    store = _mk_store_with_doc(tmp_path, doc="d", text="slow home")
    store.faults = _SlowWorkersOnly()
    hyd = Hydrator(store, workers=1, attempt_timeout_s=0.01,
                   max_attempts=1, gate_wait_s=0.001,
                   sync_wait_s=5.0, defer_budget_s=10.0)
    try:
        item = SimpleNamespace(doc_id="d", n_ops=1, epoch=-1, trace=None)
        keep, defer, dropped = hyd.flush_gate(0, [item])
        assert defer and not keep and not dropped
        keep, defer, dropped = hyd.flush_gate(0, [item])
        assert keep and not defer and not dropped
        assert hyd.status("d") == "warm"
        c = hyd.counters_snapshot()
        assert c["defer_escalations"] == 1 and c["deferrals"] == 1
        assert hyd.resolve("d").checkout_tip().snapshot() == "slow home"
    finally:
        hyd.stop(checkpoint=False)


def test_defer_budget_exhaustion_quarantines(tmp_path):
    store = _mk_store_with_doc(tmp_path, doc="stuck")
    store.faults = _SlowNTimes(100, slow_s=5.0)
    hyd = Hydrator(store, workers=1, attempt_timeout_s=0.01,
                   max_attempts=1, gate_wait_s=0.001,
                   defer_budget_s=0.02)
    try:
        item = SimpleNamespace(doc_id="stuck", n_ops=1, epoch=-1,
                               trace=None)
        keep, defer, dropped = hyd.flush_gate(0, [item])
        assert defer and not keep and not dropped
        time.sleep(0.05)       # let the defer budget lapse
        keep, defer, dropped = hyd.flush_gate(0, [item])
        assert dropped and not keep and not defer
        assert store.is_quarantined("stuck") == "hydration_stuck"
        assert hyd.counters_snapshot()["defer_gave_up"] == 1
    finally:
        hyd.stop(checkpoint=False)


# ---- eviction-to-snapshot churn parity (satellite 3) ---------------------

def test_eviction_churn_byte_parity_vs_resident_control(tmp_path):
    rng = random.Random(11)
    docs = [f"d{i}" for i in range(8)]
    store = TieredStore(str(tmp_path), compact_patch_records=4)
    for d in docs:
        store.save(d, _mk_oplog([f"[{d}] "]))
    hyd = Hydrator(store, workers=2, warm_max=3, evict_grace_s=0.0,
                   sync_wait_s=5.0)
    # always-resident control: the same edits applied to oplogs that
    # are NEVER evicted — any byte the eviction path drops shows here
    control = {d: _mk_oplog([f"[{d}] "]) for d in docs}
    try:
        for step in range(120):
            d = rng.choice(docs)
            tok = f"e{step}."
            live = hyd.resolve(d)
            pos = rng.randint(0, len(
                control[d].checkout_tip().snapshot()))
            for ol in (live, control[d]):
                ol.add_insert(ol.get_or_create_agent_id("ed"), pos, tok)
            if rng.random() < 0.2:
                # evict mid-churn, not just at LRU pressure
                hyd.evict_to_snapshot(rng.choice(docs), why="test")
        assert hyd.counters_snapshot()["evictions_to_snapshot"] > 0
        for d in docs:
            assert hyd.resolve(d).checkout_tip().snapshot() \
                == control[d].checkout_tip().snapshot(), d
        # ... and the same holds re-hydrated from disk after shutdown
        hyd.stop(checkpoint=True)
        fresh = TieredStore(str(tmp_path))
        for d in docs:
            assert fresh.load(d).checkout_tip().snapshot() \
                == control[d].checkout_tip().snapshot(), d
    finally:
        hyd.stop(checkpoint=False)


def test_eviction_aborts_when_append_races_the_snapshot(tmp_path):
    store = _mk_store_with_doc(tmp_path, doc="d", text="base ")

    class _RacingStore:
        """Proxy whose save() appends to the live oplog AFTER the
        snapshot encode returns — the exact race eviction must detect
        via the persisted-op-count recheck."""

        def __init__(self, inner):
            self._inner = inner
            self.racer = None

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def save(self, doc_id, oplog, oplog_lock=None):
            n = self._inner.save(doc_id, oplog, oplog_lock=oplog_lock)
            if self.racer is not None:
                self.racer(oplog)
            return n

    proxy = _RacingStore(store)
    hyd = Hydrator(proxy, workers=1, sync_wait_s=5.0)
    try:
        ol = hyd.resolve("d")

        def racer(target):
            target.add_insert(
                target.get_or_create_agent_id("late"), 0, "racing-op ")

        proxy.racer = racer
        assert hyd.evict_to_snapshot("d", why="test") is False
        proxy.racer = None
        c = hyd.counters_snapshot()
        assert c["eviction_aborts"] == 1
        # the doc stayed warm: the racing op is still resident
        assert hyd.resolve("d") is ol
        assert "racing-op" in ol.checkout_tip().snapshot()
        # with the race gone the next eviction lands and persists it
        assert hyd.evict_to_snapshot("d", why="test") is True
        assert "racing-op" in \
            store.load("d").checkout_tip().snapshot()
    finally:
        hyd.stop(checkpoint=False)


# ---- SessionBank eviction routing (satellite 6) --------------------------

class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))


def test_bank_evict_reports_pending_ops_and_snapshot_routing():
    from diamond_types_tpu.serve.bank import SessionBank
    bank = SessionBank(0, max_sessions=4, engine="host")
    bank.recorder = _Recorder()
    requested = []
    bank.snapshot_hook = lambda d, pending: (
        requested.append((d, pending)) or True)
    ol = _mk_oplog(["pending state "])
    bank.session("doc", ol)
    assert bank.evict("doc") is True
    assert requested and requested[0][0] == "doc"
    assert requested[0][1] >= 0
    evs = [f for k, f in bank.recorder.events if k == "session_evicted"]
    assert evs and evs[0]["doc"] == "doc"
    assert evs[0]["snapshotted"] is True
    assert evs[0]["pending_ops"] == requested[0][1]
    # hook failure must not wedge the eviction path
    bank.session("doc2", ol)
    bank.snapshot_hook = lambda d, pending: 1 / 0
    assert bank.evict("doc2") is True


# ---- metrics v7 + prom (satellite 5) -------------------------------------

def test_metrics_v7_hydration_block_and_prom_families():
    m = ServeMetrics(2, 4, 64)
    m.record_hydration("prefetches")
    m.record_hydration("evictions_to_snapshot", 3)
    m.observe_cold_start(0.012)
    snap = m.snapshot()
    assert snap["version"] == 13
    assert set(HYDRATION_KEYS) <= set(snap["hydration"])
    assert snap["hydration"]["prefetches"] == 1
    assert snap["hydration"]["evictions_to_snapshot"] == 3
    assert snap["latencies"]["hydration_cold_start"]["count"] == 1
    from diamond_types_tpu.obs.prom import render_metrics
    text = render_metrics({"serve": snap})
    assert "dt_serve_hydration_prefetches_total 1" in text
    assert "dt_serve_hydration_evictions_to_snapshot_total 3" in text
    assert "hydration_cold_start" in text


# ---- scheduler integration + soak smoke ----------------------------------

def test_scheduler_rejects_quarantined_and_flushes_rest(tmp_path):
    from diamond_types_tpu.serve.scheduler import MergeScheduler
    store = TieredStore(str(tmp_path))
    for d in ("a", "b", "bad"):
        store.save(d, _mk_oplog([f"[{d}] "]))
    with open(store.path("bad"), "r+b") as f:
        f.write(b"\xff" * os.path.getsize(store.path("bad")))
    hyd = Hydrator(store, workers=1, sync_wait_s=5.0)
    sched = MergeScheduler(2, hyd.resolve, engine="host",
                           flush_deadline_s=0.01)
    sched.attach_hydrator(hyd)
    try:
        # quarantine is discovered at hydration time...
        assert sched.submit("bad")["accepted"] is True
        sched.drain()
        # ...after which admission itself rejects, typed
        time.sleep(0.05)
        r = sched.submit("bad")
        assert r == {"accepted": False, "shard": r["shard"],
                     "reason": "quarantined"}
        for d in ("a", "b"):
            ol = hyd.resolve(d)
            ol.add_insert(ol.get_or_create_agent_id("ed"),
                          len(ol.checkout_tip().snapshot()), "edited")
            assert sched.submit(d)["accepted"] is True
        sched.drain()
        for d in ("a", "b"):
            assert sched.text(d) == f"[{d}] edited"
        assert hyd.counters_snapshot()["flush_leaks"] == 0
    finally:
        sched.stop_pump(drain=False)
        hyd.stop(checkpoint=False)


def test_storage_soak_smoke_all_faults():
    from diamond_types_tpu.storage.soak import run_storage_soak
    rep = run_storage_soak(docs=16, warm=4, rounds=3,
                           edits_per_round=10, shards=2, seed=5,
                           compact_every=6, churn=True, crash=True,
                           slow=True)
    assert rep["ok"], rep
    assert rep["byte_mismatches"] == 0
    assert rep["quarantine_match"] and rep["quarantine_leaks"] == 0
    assert rep["crashes"] == 1 and rep["compaction_kills"] == 3
    assert rep["lock_witness"]["acyclic"]
    assert rep["lock_witness"]["violation_count"] == 0
