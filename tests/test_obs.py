"""Observability tests (diamond_types_tpu/obs/): histogram math vs.
brute force, trace-context propagation across a proxied write, the
flight recorder's bounded ring, Prometheus rendering validity, and the
disabled-path zero-allocation contract. Tier-1 safe: in-process
servers on ephemeral ports, no TPU."""

import json
import random
import re
import threading
import time
import tracemalloc
import urllib.request

import pytest

from diamond_types_tpu.obs import Observability
from diamond_types_tpu.obs.hist import BOUNDS, Histogram, HistogramSet
from diamond_types_tpu.obs.prom import (CONTENT_TYPE, escape_label_value,
                                        render_metrics)
from diamond_types_tpu.obs.recorder import FlightRecorder
from diamond_types_tpu.obs.trace import (NOOP_SPAN, TRACE_HEADER, Tracer,
                                         format_context, parse_header)

pytestmark = pytest.mark.obs


# ---- histograms ----------------------------------------------------------

def test_histogram_counts_sum_max_exact():
    rng = random.Random(11)
    vals = [rng.uniform(1e-7, 5.0) for _ in range(500)]
    h = Histogram()
    for v in vals:
        h.record(v)
    s = h.snapshot()
    assert s["count"] == len(vals)
    assert s["sum"] == pytest.approx(sum(vals))
    assert s["max"] == pytest.approx(max(vals))


def test_histogram_quantiles_vs_bruteforce():
    """Log2 buckets bound the quantile error: the reported value must
    bracket the true quantile within one bucket (a factor of 2)."""
    rng = random.Random(7)
    # mixed scales, like real latencies: µs bookkeeping to 100ms flushes
    vals = [rng.choice([1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1])
            * rng.uniform(1.0, 2.0) for _ in range(2000)]
    h = Histogram()
    for v in vals:
        h.record(v)
    vals.sort()
    for q in (0.5, 0.9, 0.99):
        true = vals[min(int(q * len(vals)), len(vals) - 1)]
        got = h.quantile(q)
        assert true / 2 <= got <= true * 2, (q, true, got)
    s = h.snapshot()
    assert s["p50"] <= s["p90"] <= s["p99"]


def test_histogram_bucket_upper_inclusive():
    """Prometheus le semantics: a value exactly on a bucket bound is
    counted by that bound's cumulative bucket."""
    h = Histogram()
    for b in BOUNDS[:6]:
        h.record(b)
    buckets = dict()
    for le, cum in h.snapshot()["buckets"]:
        buckets[le] = cum
    for i, b in enumerate(BOUNDS[:6]):
        assert buckets[b] == i + 1, (b, buckets)


def test_histogram_empty_and_overflow():
    h = Histogram()
    s = h.snapshot()
    assert s["count"] == 0 and s["p99"] == 0.0
    h.record(1e9)   # beyond the last bound -> overflow bucket
    s = h.snapshot()
    assert s["count"] == 1
    assert s["buckets"][-1] == ["+Inf", 1] or \
        tuple(s["buckets"][-1]) == ("+Inf", 1)


def test_histogram_set_label_grouping():
    hs = HistogramSet()
    hs.observe("http_request", 0.01, endpoint="edit", method="POST")
    hs.observe("http_request", 0.02, endpoint="edit", method="POST")
    hs.observe("http_request", 0.03, endpoint="state", method="GET")
    snap = hs.snapshot()
    rows = snap["http_request"]
    by_ep = {r["labels"]["endpoint"]: r for r in rows}
    assert by_ep["edit"]["count"] == 2
    assert by_ep["state"]["count"] == 1


# ---- flight recorder -----------------------------------------------------

def test_recorder_bounded_and_ordered():
    r = FlightRecorder(capacity=8)
    for i in range(20):
        r.record("ev", i=i)
    dump = r.dump()
    assert len(dump) == 8
    seqs = [e["seq"] for e in dump]
    assert seqs == sorted(seqs)           # oldest-first
    assert [e["i"] for e in dump] == list(range(12, 20))  # last 8 kept
    st = r.stats()
    assert st["recorded"] == 20
    assert st["buffered"] == 8
    assert st["dropped"] == 12
    assert r.tail(3) == dump[-3:]


def test_recorder_disabled_is_noop():
    r = FlightRecorder(capacity=8, enabled=False)
    for i in range(5):
        r.record("ev", i=i)
    assert r.dump() == []
    assert r.stats()["recorded"] == 0


# ---- trace context -------------------------------------------------------

def test_trace_header_roundtrip():
    tr = Tracer(sample_rate=1.0, seed=1)
    span = tr.start("root")
    hdr = span.header()
    ctx = parse_header(hdr)
    assert ctx is not None
    assert ctx.trace_id == span.context().trace_id
    assert ctx.span_id == span.context().span_id
    assert ctx.sampled
    assert format_context(ctx) == hdr
    span.end()


def test_trace_header_malformed_rejected():
    for bad in ("", "x", "ab-cd", "zz-11-1", "a-b-1-extra",
                "f" * 33 + "-11-1", "11-" + "f" * 33 + "-1", None):
        assert parse_header(bad) is None
    # any flags value other than "1" is valid-but-unsampled, not junk
    ctx = parse_header("ab-cd-2")
    assert ctx is not None and not ctx.sampled


def test_parent_sampling_inherited():
    tr = Tracer(sample_rate=0.0, seed=1)   # head-samples nothing...
    root = tr.start("r")
    assert root is NOOP_SPAN
    # ...but a sampled incoming context forces the continuation
    ctx = parse_header("00000000000000aa-00000000000000bb-1")
    child = tr.start("c", parent=ctx)
    assert child.sampled
    assert child.context().trace_id == ctx.trace_id
    child.end()
    # and an unsampled parent pins the whole subtree out
    unsampled = parse_header("00000000000000aa-00000000000000bb-0")
    assert tr.start("c2", parent=unsampled) is NOOP_SPAN


def test_disabled_tracer_single_branch_zero_alloc():
    """The disabled path is ONE branch returning the NOOP singleton —
    pinned by identity and by tracemalloc showing zero allocations
    attributed to obs/trace.py across 200 start/annotate/end cycles."""
    tr = Tracer(enabled=False)
    assert tr.start("x") is NOOP_SPAN
    assert tr.start("x", force=True) is NOOP_SPAN
    import diamond_types_tpu.obs.trace as trace_mod
    tr.start("warmup").end()   # touch everything once before measuring
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(200):
        sp = tr.start("x")
        sp.annotate(k=1)
        sp.end()
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grew = [st for st in after.compare_to(before, "filename")
            if st.size_diff > 0
            and st.traceback[0].filename == trace_mod.__file__]
    assert not grew, [str(g) for g in grew]


# ---- Prometheus rendering ------------------------------------------------

def test_escape_label_value():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="'
    r'(\\.|[^"\\])*"(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})?'
    r' -?([0-9.e+-]+|\+Inf|NaN)$')


def _check_prom(text: str) -> None:
    """Shape check: every line is a comment or a valid sample, one
    # TYPE per family, no duplicate (name, labels) sample."""
    seen_types = set()
    seen_samples = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE"):
            fam = line.split()[2]
            assert fam not in seen_types, f"duplicate TYPE {fam}"
            seen_types.add(fam)
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        key = line.rsplit(" ", 1)[0]
        assert key not in seen_samples, f"duplicate sample {key}"
        seen_samples.add(key)


def test_prom_renderer_from_live_snapshots():
    from diamond_types_tpu.replicate.metrics import ReplicationMetrics
    from diamond_types_tpu.serve.metrics import ServeMetrics
    sm = ServeMetrics(2, flush_docs=4, max_pending=64)
    sm.record_flush(0, 2, 5, "size", dur_s=0.003)
    sm.observe_queue(1, 3)
    rm = ReplicationMetrics()
    rm.bump("quorum", "acks", 3)
    rm.observe_handoff_latency(0.25)
    rm.observe_latency("probe", 0.001)
    obs = Observability(sample_rate=1.0)
    obs.tracer.start("t").end()
    # label values that need escaping must survive the renderer
    obs.hist.observe("http_request", 0.01, endpoint='we"ird\\pa\nth',
                     method="GET")
    obs.recorder.record("circuit_open", peer="p1")
    doc = {"serve": sm.snapshot(), "replication": rm.snapshot(),
           "obs": obs.snapshot()}
    text = render_metrics(doc)
    _check_prom(text)
    assert "dt_flush_latency_seconds_count 1" in text
    assert "dt_handoff_latency_seconds_count 1" in text
    assert 'we\\"ird\\\\pa\\nth' in text
    assert "dt_repl_quorum_acks_total 3" in text


def test_prom_renderer_handles_missing_sections():
    _check_prom(render_metrics({"serve": None, "replication": None}))


def test_prom_renders_witness_and_lint_families():
    """The concurrency-invariant tier exports through the same one-
    TYPE-per-name builder: dt_witness_* from the runtime lock witness,
    dt_lint_violations_total{rule} from the last published dt-lint
    report (zero-filled per rule on a clean run)."""
    from diamond_types_tpu.analysis import (make_lock, witness_disable,
                                            witness_enable,
                                            witness_reset)
    from diamond_types_tpu.analysis.lint import SEVERITY, publish_report
    witness_reset()
    witness_enable()
    try:
        outer = make_lock("t.outer", "global")
        inner = make_lock("t.inner", "shard")
        with outer:
            with inner:
                pass
    finally:
        witness_disable()
    publish_report({"files": 3, "by_rule": {r: 0 for r in SEVERITY},
                    "errors": 0, "warnings": 0, "ok": True})
    obs = Observability(enabled=False)
    text = render_metrics({"obs": obs.snapshot()})
    _check_prom(text)
    assert 'dt_witness_edges{edge="global->shard"} 1' in text
    assert "dt_witness_acyclic 1" in text
    assert "dt_witness_violations_total 0" in text
    for rule in SEVERITY:
        assert f'dt_lint_violations_total{{rule="{rule}"}} 0' in text
    assert "dt_lint_ok 1" in text
    witness_reset()


def test_replication_metrics_v3_derived_keys():
    """Satellite (a): the v2 scalar pair is derived from the v3
    histogram so old scrapers keep working."""
    from diamond_types_tpu.replicate.metrics import ReplicationMetrics
    rm = ReplicationMetrics()
    for s in (0.1, 0.3):
        rm.observe_handoff_latency(s)
    snap = rm.snapshot()
    assert snap["version"] == 8
    assert snap["latencies"]["handoff"]["count"] == 2
    assert snap["handoffs"]["latency_s_total"] == pytest.approx(0.4)
    assert snap["handoffs"]["latency_s_max"] == pytest.approx(0.3)
    assert snap["latencies"]["handoff"]["p99"] > 0


# ---- end-to-end: server + proxied trace ----------------------------------

def _serve_pair(sample_rate=1.0):
    from diamond_types_tpu.replicate import attach_replication
    from diamond_types_tpu.tools.server import serve
    httpds, addrs = [], []
    for _ in range(2):
        httpd = serve(port=0, serve_shards=2,
                      obs_opts={"sample_rate": sample_rate})
        httpds.append(httpd)
        addrs.append(f"127.0.0.1:{httpd.server_address[1]}")
    nodes = []
    for i, httpd in enumerate(httpds):
        nodes.append(attach_replication(
            httpd, addrs[i], [a for a in addrs if a != addrs[i]],
            lease_ttl_s=5.0, backoff_base_s=0.01, backoff_cap_s=0.05))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
    return httpds, nodes, addrs


def _teardown(httpds):
    for h in httpds:
        h.shutdown()
        h.server_close()


def _post(addr, path, obj):
    req = urllib.request.Request(f"http://{addr}{path}",
                                 data=json.dumps(obj).encode("utf8"))
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, json.loads(r.read())


def test_proxied_edit_yields_one_stitched_trace():
    """Acceptance: a proxied edit across a two-server mesh produces ONE
    trace — proxy hop, remote http span, ownership gate, admit, flush,
    device sync — with parentage intact across the HTTP boundary."""
    httpds, nodes, addrs = _serve_pair(sample_rate=1.0)
    try:
        # a doc owned by server 1, posted to server 0 -> proxied
        doc = next(d for d in (f"tdoc-{i}" for i in range(64))
                   if nodes[0].desired_owner(d) == addrs[1])
        status, out = _post(addrs[0], f"/doc/{doc}/edit",
                            {"agent": "tracer", "version": [],
                             "ops": [{"kind": "ins", "pos": 0,
                                      "text": "hello"}]})
        assert status == 200 and out.get("version")
        httpds[1].store.scheduler.drain()

        # HTTP spans end in the handlers' `finally`, after the
        # response bytes are on the wire — poll until both hops land
        want = {"http.doc_edit", "repl.proxy", "serve.admit",
                "serve.ownership_gate", "serve.flush",
                "serve.device_sync"}
        deadline = time.monotonic() + 3.0
        while True:
            spans = (httpds[0].store.obs.tracer.spans()
                     + httpds[1].store.obs.tracer.spans())
            roots = [s for s in spans
                     if s["name"] == "http.doc_edit"
                     and s["parent"] is None]
            mine = ([s for s in spans
                     if s["trace"] == roots[0]["trace"]]
                    if roots else [])
            names = {s["name"] for s in mine}
            hops = sum(1 for s in mine if s["name"] == "http.doc_edit")
            if (want <= names and hops == 2) or \
                    time.monotonic() > deadline:
                break
            time.sleep(0.01)
        assert roots, [s["name"] for s in spans]
        trace_id = roots[0]["trace"]
        assert want <= names, names
        assert hops == 2
        by_id = {s["span"]: s for s in mine}
        by_name = {}
        for s in mine:
            by_name.setdefault(s["name"], []).append(s)
        # every non-root span's parent is in the same trace
        for s in mine:
            if s["parent"] is not None:
                assert s["parent"] in by_id, s
        # the exact chain: root http -> proxy -> remote http -> admit
        # -> {gate, and flush -> device_sync}
        proxy = by_name["repl.proxy"][0]
        assert proxy["parent"] == roots[0]["span"]
        remote_http = [s for s in by_name["http.doc_edit"]
                       if s["parent"] == proxy["span"]]
        assert remote_http
        admit = by_name["serve.admit"][0]
        assert admit["parent"] == remote_http[0]["span"]
        assert by_name["serve.ownership_gate"][0]["parent"] \
            == admit["span"]
        flush = by_name["serve.flush"][0]
        assert flush["parent"] == admit["span"]
        assert by_name["serve.device_sync"][0]["parent"] \
            == flush["span"]
        # the mutation itself landed (proxied, not just traced)
        with urllib.request.urlopen(f"http://{addrs[1]}/doc/{doc}",
                                    timeout=5) as r:
            assert r.read().decode("utf8") == "hello"
    finally:
        _teardown(httpds)


def test_metrics_endpoint_formats_and_debug_events():
    """Satellite (b) + acceptance: /metrics serves JSON by default and
    Prometheus text with `?format=prom`, both with Cache-Control:
    no-store; dt_flush_latency_seconds shows non-zero counts after
    traffic; /debug/events dumps the flight-recorder ring."""
    from diamond_types_tpu.tools.server import serve
    httpd = serve(port=0, serve_shards=2,
                  obs_opts={"sample_rate": 1.0})
    addr = f"127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        for i in range(3):
            _post(addr, f"/doc/m{i}/edit",
                  {"agent": "a", "version": [],
                   "ops": [{"kind": "ins", "pos": 0, "text": "x"}]})
        httpd.store.scheduler.drain()
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=5) as r:
            assert r.headers["Cache-Control"] == "no-store"
            assert r.headers["Content-Type"].startswith(
                "application/json")
            doc = json.loads(r.read())
        assert doc["serve"]["version"] == 13
        assert doc["serve"]["latencies"]["flush"]["count"] >= 1
        assert doc["obs"]["trace"]["started"] >= 1
        assert any(row["count"] >= 1
                   for row in doc["obs"]["http"]["http_request"])
        with urllib.request.urlopen(
                f"http://{addr}/metrics?format=prom", timeout=5) as r:
            assert r.headers["Cache-Control"] == "no-store"
            assert r.headers["Content-Type"] == CONTENT_TYPE
            text = r.read().decode("utf8")
        _check_prom(text)
        m = re.search(r"^dt_flush_latency_seconds_count (\d+)$", text,
                      re.M)
        assert m and int(m.group(1)) >= 1, "flush histogram not exposed"
        with urllib.request.urlopen(f"http://{addr}/debug/events",
                                    timeout=5) as r:
            ev = json.loads(r.read())
        assert "events" in ev and "recorded" in ev
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_unsampled_requests_skip_span_buffer():
    """At sample_rate=0 the server's request path must produce zero
    buffered spans (histograms still record — they are always on)."""
    from diamond_types_tpu.tools.server import serve
    httpd = serve(port=0, serve_shards=2,
                  obs_opts={"sample_rate": 0.0})
    addr = f"127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        _post(addr, "/doc/z/edit",
              {"agent": "a", "version": [],
               "ops": [{"kind": "ins", "pos": 0, "text": "y"}]})
        obs = httpd.store.obs
        assert obs.tracer.spans() == []
        assert obs.tracer.stats()["sampled_out"] >= 1
        # the histogram records in the handler's `finally`, which runs
        # after the response hits the wire — give it a beat
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            rows = obs.hist.snapshot().get("http_request", [])
            if sum(r["count"] for r in rows) >= 1:
                break
            time.sleep(0.01)
        assert sum(r["count"] for r in rows) >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
