"""OT bridge conformance: the reference's golden vectors
(reference: test_data/ot/*.json, consumed by diamond-types-old
src/list/ot/ot.rs:294-307)."""

import json
import os

import pytest

from diamond_types_tpu.text import ot
from tests.conftest import reference_path

DATA = reference_path("test_data", "ot")


def load(name):
    with open(os.path.join(DATA, name)) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.parametrize("i,case", list(enumerate(load("apply.json"))))
def test_apply_golden(i, case):
    assert ot.apply(case["str"], case["op"]) == case["result"]


@pytest.mark.parametrize("i,case", list(enumerate(load("compose.json"))))
def test_compose_golden(i, case):
    assert ot.compose(case["op1"], case["op2"]) == ot.normalize(case["result"])


@pytest.mark.parametrize("i,case", list(enumerate(load("transform.json"))))
def test_transform_golden(i, case):
    got = ot.transform(case["op"], case["otherOp"], case["side"])
    assert got == ot.normalize(case["result"])


def test_xf_stream_to_traversal():
    from diamond_types_tpu import OpLog
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    b = ol.get_or_create_agent_id("bob")
    ol.add_insert_at(a, [], 0, "hello world")
    v = ol.version
    ol.add_insert_at(a, v, 5, "!")
    ol.add_delete_at(b, v, 0, 5)
    trav = ot.xf_stream_to_traversal(ol.iter_xf_operations())
    assert ot.apply("", trav) == ol.checkout_tip().snapshot()

    # Incremental: a dumb client at `v` can catch up with one traversal op.
    trav2 = ot.xf_stream_to_traversal(
        ol.iter_xf_operations_from(v, ol.version))
    assert ot.apply("hello world", trav2) == ol.checkout_tip().snapshot()
