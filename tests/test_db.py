"""Multi-CRDT document tests (reference: experimental OpLog/Branch in
src/oplog.rs, src/branch.rs; SerializedOps exchange §3.5)."""

import random

import pytest

from diamond_types_tpu.db.doc import Doc, KIND_MAP, KIND_TEXT, ROOT_CRDT


def test_map_and_text_basic():
    d = Doc()
    a = d.get_or_create_agent_id("alice")
    d.map_set(a, ROOT_CRDT, "title", ("prim", "my doc")[1])
    d.map_set(a, ROOT_CRDT, "count", 42)
    body = d.map_create_crdt(a, ROOT_CRDT, "body", KIND_TEXT)
    d.text_insert(a, body, 0, "hello world")
    d.text_delete(a, body, 5, 11)

    out = d.checkout()
    assert out["title"] == "my doc"
    assert out["count"] == 42
    assert out["body"] == "hello"


def test_nested_maps():
    d = Doc()
    a = d.get_or_create_agent_id("alice")
    inner = d.map_create_crdt(a, ROOT_CRDT, "meta", KIND_MAP)
    d.map_set(a, inner, "lang", "en")
    assert d.checkout() == {"meta": {"lang": "en"}}


def test_register_conflict_resolution_deterministic():
    d1 = Doc()
    a = d1.get_or_create_agent_id("alice")
    d1.map_set(a, ROOT_CRDT, "x", 1)
    base = d1.version

    d2 = Doc()
    d2.merge_ops(d1.ops_since([]))
    b = d2.get_or_create_agent_id("bob")

    # Concurrent sets of the same key.
    d1.map_set(a, ROOT_CRDT, "x", 10)
    d2.map_set(b, ROOT_CRDT, "x", 20)

    d1.merge_ops(d2.ops_since(base))
    d2.merge_ops(d1.ops_since(base))

    c1, c2 = d1.checkout(), d2.checkout()
    assert c1["x"] == c2["x"] == 20  # bob > alice by agent-name tie-break
    assert c1["_conflicts"]["x"] == [10]


def test_concurrent_text_edits_converge():
    d1 = Doc()
    a = d1.get_or_create_agent_id("alice")
    body = d1.map_create_crdt(a, ROOT_CRDT, "body", KIND_TEXT)
    d1.text_insert(a, body, 0, "shared base ")
    d2 = Doc()
    d2.merge_ops(d1.ops_since([]))
    b = d2.get_or_create_agent_id("bob")
    base = d1.version

    d1.text_insert(a, body, 12, "alice-bit")
    body2 = next(iter(d2.texts))
    d2.text_insert(b, body2, 12, "bob-bit")

    d1.merge_ops(d2.ops_since(base))
    d2.merge_ops(d1.ops_since(base))
    t1 = d1.checkout()["body"]
    t2 = d2.checkout()["body"]
    assert t1 == t2
    assert "alice-bit" in t1 and "bob-bit" in t1


@pytest.mark.parametrize("seed", range(10))
def test_db_fuzz_convergence(seed):
    rng = random.Random(seed)
    docs = []
    for name in ("alice", "bob"):
        d = Doc()
        d.get_or_create_agent_id(name)
        docs.append(d)
    # Shared text crdt created by alice, synced to bob.
    t = docs[0].map_create_crdt(0, ROOT_CRDT, "t", KIND_TEXT)
    docs[1].merge_ops(docs[0].ops_since([]))

    keys = ["a", "b", "c"]
    for step in range(25):
        di = rng.randrange(2)
        d = docs[di]
        agent = 0 if di == 0 else d.get_or_create_agent_id("bob")
        choice = rng.random()
        if choice < 0.4:
            d.map_set(agent, ROOT_CRDT, rng.choice(keys), rng.randint(0, 99))
        else:
            tid = next(iter(d.texts))
            cur = d.checkout_text(tid)
            if cur and choice < 0.6:
                s = rng.randrange(len(cur))
                e = min(len(cur), s + rng.randint(1, 3))
                d.text_delete(agent, tid, s, e)
            else:
                pos = rng.randint(0, len(cur))
                d.text_insert(agent, tid, pos, rng.choice("xyz") * rng.randint(1, 3))
        if rng.random() < 0.3:
            docs[0].merge_ops(docs[1].ops_since([]))
            docs[1].merge_ops(docs[0].ops_since([]))

    docs[0].merge_ops(docs[1].ops_since([]))
    docs[1].merge_ops(docs[0].ops_since([]))
    assert docs[0].checkout() == docs[1].checkout()
