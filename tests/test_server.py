"""In-process server/client sync test (reference: wiki demo, SURVEY.md L8)."""

import threading

from diamond_types_tpu.tools.server import SyncClient, serve


def test_two_clients_collaborate(tmp_path):
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        a = SyncClient(base, "note", "alice")
        b = SyncClient(base, "note", "bob")

        a.insert(0, "Hello from alice. ")
        a.sync()
        b.pull()
        assert b.text() == "Hello from alice. "

        # Concurrent edits.
        b.insert(len(b.text()), "And bob!")
        a.insert(0, ">> ")
        a.sync()
        b.sync()
        a.sync()
        assert a.text() == b.text()
        assert "And bob!" in a.text() and ">> " in a.text()

        # Server persisted a .dt file readable on its own.
        httpd.RequestHandlerClass.store.flush(force=True)
        from diamond_types_tpu.encoding.decode import load_oplog
        with open(tmp_path / "note.dt", "rb") as f:
            ol = load_oplog(f.read())
        assert ol.checkout_tip().snapshot() == a.text()
    finally:
        httpd.shutdown()


def _api(base, doc, action, body):
    import json
    import urllib.request
    req = urllib.request.Request(f"{base}/doc/{doc}/{action}",
                                 data=json.dumps(body).encode("utf8"))
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


class DumbClient:
    """Python simulation of the browser editor's loop (web_assets.py):
    positional edits at a remembered version + OT traversal catch-up.
    No CRDT on the client at all."""

    def __init__(self, base, doc, agent):
        import json
        import urllib.request
        self.base, self.doc, self.agent = base, doc, agent
        with urllib.request.urlopen(f"{base}/doc/{doc}/state") as r:
            st = json.loads(r.read())
        self.text, self.version = st["text"], st["version"]

    def edit(self, ops):
        # apply locally the way a textarea already shows the user's typing
        for op in ops:
            if op["kind"] == "ins":
                p = op["pos"]
                self.text = self.text[:p] + op["text"] + self.text[p:]
            else:
                self.text = self.text[:op["start"]] + self.text[op["end"]:]
        r = _api(self.base, self.doc, "edit",
                 {"agent": self.agent, "version": self.version, "ops": ops})
        self.version = r["version"]

    def sync(self):
        from diamond_types_tpu.text import ot
        r = _api(self.base, self.doc, "changes", {"version": self.version})
        self.text = ot.apply(self.text, r["op"])
        self.version = r["version"]


def test_browser_dumb_clients_converge(tmp_path):
    """Two positional browser clients + one CRDT client, concurrent edits,
    everyone converges (reference: wiki demo end-user edit loop)."""
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        w1 = DumbClient(base, "page", "web-one")
        w1.edit([{"kind": "ins", "pos": 0, "text": "The quick brown fox"}])

        w2 = DumbClient(base, "page", "web-two")
        w2.sync()
        assert w2.text == "The quick brown fox"

        # Concurrent: w1 edits the head, w2 the tail, crdt client the middle.
        c = SyncClient(base, "page", "carol")
        c.pull()
        w1.edit([{"kind": "ins", "pos": 0, "text": ">> "}])
        w2.edit([{"kind": "del", "start": 10, "end": 16},
                 {"kind": "ins", "pos": 10, "text": "red"}])
        c.insert(4, "very ")
        c.sync()
        for cl in (w1, w2):
            cl.sync()
        c.sync()
        w1.sync()
        assert w1.text == w2.text == c.text()
        assert w1.text.startswith(">> ")
        assert "red" in w1.text and "very" in w1.text
    finally:
        httpd.shutdown()


def test_browser_pages_and_graph_endpoints(tmp_path):
    import json
    import urllib.request
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        w = DumbClient(base, "g", "web")
        w.edit([{"kind": "ins", "pos": 0, "text": "hello"}])
        w.edit([{"kind": "ins", "pos": 5, "text": " world"}])

        for page in ("/", "/edit/g", "/vis/g"):
            with urllib.request.urlopen(base + page) as r:
                html = r.read().decode("utf8")
            assert "<title>" in html or "<h1>" in html

        with urllib.request.urlopen(base + "/doc/g/graph") as r:
            g = json.loads(r.read())
        assert g["runs"] and g["runs"][0]["agent"] == "web"
        last = g["runs"][-1]["end"] - 1
        at = _api(base, "g", "at", {"lv": last})
        assert at["text"] == "hello world"
        at0 = _api(base, "g", "at", {"lv": 4})
        assert at0["text"] == "hello"
    finally:
        httpd.shutdown()


def test_edit_endpoint_rejects_bad_ops(tmp_path):
    import json
    import urllib.error
    import urllib.request
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        w = DumbClient(base, "v", "web")
        w.edit([{"kind": "ins", "pos": 0, "text": "hello"}])
        for bad in ([{"kind": "ins", "pos": 0, "text": ""}],       # empty
                    [{"kind": "ins", "pos": 99, "text": "x"}],     # range
                    [{"kind": "del", "start": 2, "end": 2}],       # empty
                    [{"kind": "del", "start": 0, "end": 99}],      # range
                    [{"kind": "nop"}]):                            # kind
            try:
                _api(base, "v", "edit",
                     {"agent": "web", "version": w.version, "ops": bad})
                raise AssertionError(f"accepted bad op {bad}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        # a batch failing validation must not half-apply: doc unchanged
        try:
            _api(base, "v", "edit", {"agent": "web", "version": w.version,
                 "ops": [{"kind": "ins", "pos": 0, "text": "A"},
                         {"kind": "del", "start": 50, "end": 60}]})
            raise AssertionError("accepted half-bad batch")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        import urllib.request as u
        with u.urlopen(f"{base}/doc/v") as r:
            assert r.read().decode() == "hello"

        # Coerced-validation hole (ADVICE r2): a float pos passes int()
        # validation but must not reach add_insert_at unconverted -> 400.
        for bad in ([{"kind": "ins", "pos": 1.5, "text": "x"}],
                    [{"kind": "ins", "pos": "2", "text": "x"}],
                    [{"kind": "del", "start": 0.5, "end": 2}]):
            try:
                _api(base, "v", "edit",
                     {"agent": "web", "version": w.version, "ops": bad})
                raise AssertionError(f"accepted non-int op {bad}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        # Malformed bodies on browser endpoints -> 400, not a closed
        # connection / handler crash (ADVICE r2).
        for action, payload in (
                ("at", {}),                      # missing lv
                ("at", {"lv": "zero"}),          # non-numeric lv
                ("at", {"lv": 10**9}),           # out of range lv
                ("at", {"lv": -1}),              # negative lv
                ("edit", {"agent": "web"}),      # missing ops
                ("edit", {"agent": 7, "version": [],
                          "ops": [{"kind": "ins", "pos": 0, "text": "x"}]}),
                ("changes", {"wait": "soon"})):  # non-numeric wait
            try:
                _api(base, "v", action, payload)
                raise AssertionError(f"accepted bad {action} {payload}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        # Raw non-JSON body -> 400 as well.
        req = urllib.request.Request(f"{base}/doc/v/at", data=b"not json")
        try:
            urllib.request.urlopen(req)
            raise AssertionError("accepted non-JSON body")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        with u.urlopen(f"{base}/doc/v") as r:
            assert r.read().decode() == "hello"
    finally:
        httpd.shutdown()


def test_flush_races_concurrent_edits(tmp_path):
    """Autosave encoding must run under the store lock: hammer /edit from
    two threads while forcing flushes; the persisted .dt must always load
    (ADVICE r2 medium: flush() used to encode outside the lock)."""
    from diamond_types_tpu.encoding.decode import load_oplog
    httpd = serve(port=0, data_dir=str(tmp_path))
    store = httpd.RequestHandlerClass.store
    store.save_interval = 0.0  # every flush() call is "due"
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        errs = []

        def hammer(name):
            try:
                w = DumbClient(base, "r", name)
                for i in range(40):
                    w.edit([{"kind": "ins", "pos": 0, "text": f"{name}{i} "}])
                    w.sync()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=hammer, args=(n,))
              for n in ("alice", "bob")]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        assert not errs
        store.flush(force=True)
        ol = load_oplog((tmp_path / "r.dt").read_bytes())
        assert len(ol) > 0 and "alice0" in ol.checkout_tip().snapshot()
    finally:
        httpd.shutdown()


def test_flush_encode_failure_backoff(tmp_path, capsys):
    """A doc whose encode persistently fails must back off exponentially
    instead of spamming a full traceback + O(doc) encode on every pass
    (ADVICE r4); a new edit cuts the backoff, a success clears it."""
    from diamond_types_tpu.tools.server import DocStore

    store = DocStore(data_dir=str(tmp_path), save_interval=0.0)

    class Bomb:
        """Stands in for an OpLog poisoned before input validation."""
        armed = True

    real_encode = None
    import diamond_types_tpu.tools.server as srv
    real_encode = srv.encode_oplog

    def fake_encode(ol, *a, **k):
        if isinstance(ol, Bomb) and ol.armed:
            raise ValueError("poisoned")
        if isinstance(ol, Bomb):
            return b"ok"
        return real_encode(ol, *a, **k)

    srv.encode_oplog = fake_encode
    try:
        bomb = Bomb()
        store.docs["bad"] = bomb
        store.mark_dirty("bad")
        for _ in range(6):
            store.flush()
        # backoff engaged: the doc is dirty with a FUTURE due time and
        # far fewer than 6 tracebacks were printed
        assert store.flush_failures["bad"] >= 1
        assert store.dirty["bad"] > __import__("time").monotonic()
        err = capsys.readouterr().err
        assert err.count("Traceback") == 1      # first failure only
        fails_before = store.flush_failures["bad"]
        # a new edit cuts the standing backoff -> prompt retry
        store.mark_dirty("bad")
        store.flush()
        assert store.flush_failures["bad"] == fails_before + 1
        # and a success clears the failure state entirely
        bomb.armed = False
        store.mark_dirty("bad")
        store.flush()
        assert "bad" not in store.flush_failures
        assert (tmp_path / "bad.dt").read_bytes() == b"ok"
    finally:
        srv.encode_oplog = real_encode


def test_flush_write_failure_remarks_dirty(tmp_path, capsys):
    """A disk-write failure (ENOSPC/EIO) on one doc must not abort the
    write loop or silently drop the already-cleared dirty flags — the
    failing doc re-enters the backoff cycle and later docs still write."""
    import diamond_types_tpu.tools.server as srv
    from diamond_types_tpu.tools.server import DocStore
    from diamond_types_tpu.text.oplog import OpLog

    store = DocStore(data_dir=str(tmp_path), save_interval=0.0)
    for name, text in (("aa", "first"), ("bb", "second")):
        ol = OpLog()
        ag = ol.get_or_create_agent_id("u")
        ol.add_insert_at(ag, [], 0, text)
        store.docs[name] = ol
        store.mark_dirty(name)

    real_replace = srv.os.replace
    def flaky_replace(src, dst):
        if dst.endswith("aa.dt"):
            raise OSError(28, "No space left on device")
        return real_replace(src, dst)

    srv.os.replace = flaky_replace
    try:
        store.flush()
        # bb still persisted despite aa's write failure; aa is re-dirty
        # with backoff and counted
        assert (tmp_path / "bb.dt").exists()
        assert not (tmp_path / "aa.dt").exists()
        assert store.flush_failures["aa"] >= 1
        assert "aa" in store.dirty and "bb" not in store.dirty
        assert "write failed" in capsys.readouterr().err
    finally:
        srv.os.replace = real_replace
    # recovery: disk "freed", edit cuts the backoff, write succeeds
    store.mark_dirty("aa")
    store.flush()
    assert (tmp_path / "aa.dt").exists()
    assert "aa" not in store.flush_failures


def test_changes_long_poll_streams_edits(tmp_path):
    """A waiting /changes request returns as soon as another client edits
    (braid-subscription equivalent of the reference wiki streaming)."""
    import time as _time
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        w = DumbClient(base, "lp", "writer")
        w.edit([{"kind": "ins", "pos": 0, "text": "start"}])
        r = DumbClient(base, "lp", "reader")
        r.sync()
        result = {}

        def waiter():
            t0 = _time.monotonic()
            resp = _api(base, "lp", "changes",
                        {"version": r.version, "wait": 10})
            result["latency"] = _time.monotonic() - t0
            result["resp"] = resp

        th = threading.Thread(target=waiter)
        th.start()
        _time.sleep(0.4)                 # waiter is now parked
        w.edit([{"kind": "ins", "pos": 5, "text": "!"}])
        th.join(timeout=8)
        assert not th.is_alive(), "long-poll never woke"
        assert result["latency"] < 5, "woke by timeout, not by notify"
        from diamond_types_tpu.text import ot
        assert ot.apply(r.text, result["resp"]["op"]) == "start!"

        # and an idle wait times out quickly with an empty traversal
        r.sync()
        t0 = _time.monotonic()
        resp = _api(base, "lp", "changes", {"version": r.version,
                                            "wait": 0.5})
        assert resp["op"] == [] and _time.monotonic() - t0 < 3
    finally:
        httpd.shutdown()


def test_history_strip_endpoint(monkeypatch):
    """/doc/{id}/history returns snapshots oldest-first. DT_SERVER_DEVICE
    routes the whole strip through ONE batched texts_at_versions call
    (tests run on the CPU backend; a real server defaults to host
    checkouts so a wedged accelerator tunnel can't hang a handler)."""
    import json
    import threading
    import urllib.request
    from diamond_types_tpu.tools.server import serve

    monkeypatch.setenv("DT_SERVER_DEVICE", "1")
    srv = serve(port=0, data_dir=None)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{port}"
        # build a concurrent doc via two pushes
        from diamond_types_tpu import OpLog
        from diamond_types_tpu.encoding.encode import ENCODE_FULL, encode_oplog
        ol = OpLog()
        a = ol.get_or_create_agent_id("a")
        b = ol.get_or_create_agent_id("b")
        v = [ol.add_insert_at(a, [], 0, "base text here")]
        ol.add_insert_at(a, v, 0, "A1 ")
        ol.add_insert_at(b, v, 14, " B1")
        blob = encode_oplog(ol, ENCODE_FULL)
        req = urllib.request.Request(base + "/doc/h1/push", data=blob)
        urllib.request.urlopen(req).read()

        req = urllib.request.Request(
            base + "/doc/h1/history",
            data=json.dumps({"n": 8}).encode("utf8"))
        out = json.loads(urllib.request.urlopen(req).read())
        snaps = out["snapshots"]
        assert len(snaps) >= 2
        assert snaps[-1]["text"] == ol.checkout_tip().snapshot()
        lvs = [s["lv"] for s in snaps]
        assert lvs == sorted(lvs)
        # every snapshot is a real historical doc
        for s in snaps:
            f = ol.cg.graph.find_dominators([s["lv"]])
            # strip versions are entry frontiers, not single-lv dominators;
            # at minimum the text matches SOME consistent version: check
            # the final one exactly (above) and types here
            assert isinstance(s["text"], str)
    finally:
        srv.shutdown()
        srv.server_close()


def test_history_strip_host_path():
    """Default (no DT_SERVER_DEVICE): host-checkout sampling, including
    the merged tip for concurrent histories."""
    from diamond_types_tpu import OpLog
    from diamond_types_tpu.tools.server import doc_history_strip
    ol = OpLog()
    a = ol.get_or_create_agent_id("a")
    b = ol.get_or_create_agent_id("b")
    v = [ol.add_insert_at(a, [], 0, "0123456789")]
    ol.add_insert_at(a, v, 0, "A")
    ol.add_insert_at(b, v, 10, "B")
    snaps = doc_history_strip(ol, 6)
    assert len(snaps) >= 2
    assert snaps[-1]["text"] == ol.checkout_tip().snapshot()
    assert [s["lv"] for s in snaps] == sorted(s["lv"] for s in snaps)


class _CrdtPeer:
    """A minimal Python twin of the in-browser CRDT peer (web_assets.
    CRDT_HTML): pushes ORIGINAL unit ops with explicit parent versions,
    pulls missing ops by summary. Exercises /doc/{id}/ops end to end."""

    def __init__(self, base, doc, name):
        import urllib.request
        self._rq = urllib.request
        self.base, self.doc, self.name = base, doc, name
        self.seq = 0
        self.frontier = []         # [[agent, seq]...]
        self.pending = []
        self.known = {}            # agent -> next seq

    def edit_ins(self, pos, text):
        for i, ch in enumerate(text):
            op = {"agent": self.name, "seq": self.seq,
                  "parents": self.frontier, "kind": "ins",
                  "pos": pos + i, "content": ch}
            self.frontier = [[self.name, self.seq]]
            self.seq += 1
            self.pending.append(op)
        self.known[self.name] = self.seq

    def edit_del(self, pos, n):
        for _ in range(n):
            op = {"agent": self.name, "seq": self.seq,
                  "parents": self.frontier, "kind": "del",
                  "pos": pos, "len": 1}
            self.frontier = [[self.name, self.seq]]
            self.seq += 1
            self.pending.append(op)
        self.known[self.name] = self.seq

    def sync(self):
        import json
        body = json.dumps({"have": self.known, "push": self.pending})
        req = self._rq.Request(f"{self.base}/doc/{self.doc}/ops",
                               data=body.encode("utf8"))
        out = json.loads(self._rq.urlopen(req).read())
        self.pending = []
        for row in out["ops"]:
            units = len(row.get("content") or "") if row["kind"] == "ins" \
                else row["len"]
            nxt = self.known.get(row["agent"], 0)
            self.known[row["agent"]] = max(nxt, row["seq"] + units)
        f = {a: s for a, s in self.frontier}
        for a, s in out["version"]:
            if a != self.name:
                f[a] = max(f.get(a, -1), s)
        self.frontier = [[a, s] for a, s in f.items()]
        return out


def _boot_server(tmp_path=None):
    import threading
    from diamond_types_tpu.tools.server import serve
    srv = serve(port=0, data_dir=None)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{port}"


def test_crdt_peer_protocol_concurrent():
    """Two peers edit OFFLINE from a shared version, then sync: the
    server folds their original ops through the CRDT; pulled rows carry
    explicit parents so a browser engine can merge locally."""
    srv, base = _boot_server()
    try:
        p1 = _CrdtPeer(base, "cdoc", "anna")
        p2 = _CrdtPeer(base, "cdoc", "bert")
        p1.edit_ins(0, "hello world")
        p1.sync()
        p2.sync()                      # bert pulls anna's ops
        # both edit concurrently (offline) at the same gap
        p1.edit_ins(5, "-A")
        p2.edit_ins(5, "-B")
        p1.edit_del(0, 1)              # anna also deletes 'h'
        p1.sync()
        p2.sync()
        p1.sync()
        # server text is the converged CRDT result
        store = srv.RequestHandlerClass.store
        ol = store.get("cdoc")
        text = ol.checkout_tip().snapshot()
        assert "-A" in text and "-B" in text
        assert text.startswith("ello") and text.endswith("world")
        # a fresh peer pulling everything sees rows that rebuild the doc
        p3 = _CrdtPeer(base, "cdoc", "cara")
        out = p3.sync()
        total_units = sum(len(r.get("content") or "") if r["kind"] == "ins"
                          else r["len"] for r in out["ops"])
        assert total_units == len(ol)
        # idempotent re-push: replaying anna's first op is a no-op
        p4 = _CrdtPeer(base, "cdoc", "anna")
        p4.seq = 0
        p4.edit_ins(0, "h")            # same (anna, 0) id
        p4.pending[0]["parents"] = []
        p4.sync()
        assert ol.checkout_tip().snapshot() == text
    finally:
        srv.shutdown()
        srv.server_close()


def test_crdt_peer_offline_convergence_order_free():
    """Sync order must not matter (op exchange is causal + idempotent)."""
    srv, base = _boot_server()
    try:
        a = _CrdtPeer(base, "odoc", "aa")
        b = _CrdtPeer(base, "odoc", "bb")
        a.edit_ins(0, "base ")
        a.sync()
        b.sync()
        a.edit_ins(5, "AAA")
        b.edit_ins(5, "BBB")
        b.sync()                       # reversed order vs previous test
        a.sync()
        b.sync()
        store = srv.RequestHandlerClass.store
        text = store.get("odoc").checkout_tip().snapshot()
        assert text == "base AAABBB" or text == "base BBBAAA"
        # deterministic: agent 'aa' < 'bb' -> AAA first
        assert text == "base AAABBB"
    finally:
        srv.shutdown()
        srv.server_close()


def test_crdt_ops_endpoint_rejects_out_of_range(tmp_path):
    """ADVICE r3 (high): /doc/{id}/ops must validate pos/len against the
    document AT THE OP'S PARENTS before mutating — an accepted
    out-of-range op is persisted and poisons every future merge."""
    import json
    import urllib.error
    import urllib.request
    srv, base = _boot_server()
    try:
        p = _CrdtPeer(base, "vdoc", "anna")
        p.edit_ins(0, "hello")
        p.sync()
        store = srv.RequestHandlerClass.store
        ol = store.get("vdoc")
        assert ol.checkout_tip().snapshot() == "hello"
        frontier = [["anna", 4]]

        def push(op):
            body = json.dumps({"have": {}, "push": [op]}).encode("utf8")
            req = urllib.request.Request(base + "/doc/vdoc/ops", data=body)
            return urllib.request.urlopen(req)

        bad = [
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "ins", "pos": 999, "content": "X"},      # ins > len
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "ins", "pos": -1, "content": "X"},       # negative
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "ins", "pos": 0, "content": ""},         # empty ins
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "del", "pos": 3, "len": 99},             # del > len
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "del", "pos": 0, "len": 0},              # empty del
            {"agent": "evil", "seq": 0, "parents": frontier,
             "kind": "del", "pos": -2, "len": 1},             # negative
        ]
        for op in bad:
            try:
                push(op)
                raise AssertionError(f"accepted bad op {op}")
            except urllib.error.HTTPError as e:
                assert e.code == 400, op
        # nothing was persisted; the doc still merges cleanly
        assert ol.checkout_tip().snapshot() == "hello"
        # boundary ops ARE valid: ins at len, del of last char
        push({"agent": "evil", "seq": 0, "parents": frontier,
              "kind": "ins", "pos": 5, "content": "!"})
        push({"agent": "evil", "seq": 1, "parents": [["evil", 0]],
              "kind": "del", "pos": 5, "len": 1})
        assert ol.checkout_tip().snapshot() == "hello"
    finally:
        srv.shutdown()
        srv.server_close()


def test_crdt_ops_minimal_frontier_stored(tmp_path):
    """ADVICE r3 (low): clients track frontiers as per-agent max-seq maps,
    so pushed parents may include dominated heads; the server must store
    the MINIMAL frontier (reference invariant: frontiers are minimal)."""
    import json
    import urllib.request
    srv, base = _boot_server()
    try:
        a = _CrdtPeer(base, "mdoc", "aa")
        a.edit_ins(0, "xy")
        a.sync()
        b = _CrdtPeer(base, "mdoc", "bb")
        b.sync()
        b.edit_ins(2, "z")   # bb's op builds on aa's tip
        b.sync()
        # now push an op whose parents list BOTH aa's tip (dominated by
        # bb's op) and bb's op — the max-seq-map shape from the advice
        body = json.dumps({"have": {}, "push": [
            {"agent": "cc", "seq": 0,
             "parents": [["aa", 1], ["bb", 0]],
             "kind": "ins", "pos": 3, "content": "!"}]}).encode("utf8")
        urllib.request.urlopen(
            urllib.request.Request(base + "/doc/mdoc/ops", data=body))
        store = srv.RequestHandlerClass.store
        ol = store.get("mdoc")
        lv = ol.cg.remote_to_local_frontier([("cc", 0)])[0]
        parents = ol.cg.graph.parents_at(lv)
        # minimal: only bb's op (aa's tip is its ancestor)
        assert list(parents) == \
            list(ol.cg.remote_to_local_frontier([("bb", 0)])), \
            f"non-minimal parents stored: {parents}"
        assert ol.checkout_tip().snapshot() == "xyz!"
    finally:
        srv.shutdown()
        srv.server_close()


def test_crdt_ops_rejects_lone_surrogates():
    """JSON delivers lone surrogates; accepting one poisons every later
    encode (utf-8 wire / utf-32 arena) and breaks the flush pass."""
    import json
    import urllib.error
    import urllib.request
    srv, base = _boot_server()
    try:
        def push(op):
            body = json.dumps({"push": [op]}).encode("utf8",
                                                     "surrogatepass")
            req = urllib.request.Request(base + "/doc/s/ops", data=body)
            return urllib.request.urlopen(req)

        push({"agent": "ok", "seq": 0, "parents": [],
              "kind": "ins", "pos": 0, "content": "hi"})
        for op in (
            {"agent": "evil", "seq": 0, "parents": [["ok", 1]],
             "kind": "ins", "pos": 0, "content": "\ud800"},
            {"agent": "ev\udfffil", "seq": 0, "parents": [["ok", 1]],
             "kind": "ins", "pos": 0, "content": "x"},
        ):
            try:
                push(op)
                raise AssertionError(f"accepted surrogate op {op!r}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        store = srv.RequestHandlerClass.store
        ol = store.get("s")
        # the doc still encodes (flush path) and reads back
        from diamond_types_tpu.encoding.encode import (ENCODE_FULL,
                                                       encode_oplog)
        encode_oplog(ol, ENCODE_FULL)
        assert ol.checkout_tip().snapshot() == "hi"
    finally:
        srv.shutdown()
        srv.server_close()


def test_dumb_client_astral_positions(tmp_path):
    """Browser endpoints speak CODE-POINT positions (the fixed JS clients
    diff over Array.from; raw UTF-16 indices would drift past astral
    chars). The Python DumbClient has code-point semantics natively —
    this pins the contract end to end across /edit + /changes with
    astral content."""
    import threading
    from diamond_types_tpu.tools.server import serve
    httpd = serve(port=0, data_dir=str(tmp_path))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        w1 = DumbClient(base, "astro", "web-one")
        w1.edit([{"kind": "ins", "pos": 0,
                  "text": "a\U0001F600b\U0001F3F4c"}])   # 5 code points
        w2 = DumbClient(base, "astro", "web-two")
        w2.sync()
        assert w2.text == "a\U0001F600b\U0001F3F4c"
        # edit AFTER the astral chars: pos 4 = before 'c' in code points
        w2.edit([{"kind": "ins", "pos": 4, "text": "!"}])
        w1.edit([{"kind": "del", "start": 1, "end": 2}])  # delete emoji
        w1.sync()
        w2.sync()
        w1.sync()
        assert w1.text == w2.text == "ab\U0001F3F4!c"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_crdt_peer_astral_unit_ops():
    """The /ops peer protocol is code-point addressed: run rows expand
    into one unit op per CODE POINT (the fixed JS pull loop uses
    Array.from; unit-indexing would split astral chars into lone
    surrogates with over-counted seqs)."""
    srv, base = _boot_server()
    try:
        p1 = _CrdtPeer(base, "adoc", "anna")
        p1.edit_ins(0, "x\U0001F600y")     # 3 code points, 3 unit ops
        p1.sync()
        p2 = _CrdtPeer(base, "adoc", "bert")
        out = p2.sync()
        total_units = sum(len(r.get("content") or "") if r["kind"] == "ins"
                          else r["len"] for r in out["ops"])
        assert total_units == 3            # not 4 UTF-16 units
        assert p2.known["anna"] == 3       # seq accounting by code point
        p2.edit_ins(2, "\U0001F3F4")       # insert BETWEEN emoji and y
        p2.sync()
        p1.sync()
        store = srv.RequestHandlerClass.store
        text = store.get("adoc").checkout_tip().snapshot()
        assert text == "x\U0001F600\U0001F3F4y"
    finally:
        srv.shutdown()
        srv.server_close()
