"""In-process server/client sync test (reference: wiki demo, SURVEY.md L8)."""

import threading

from diamond_types_tpu.tools.server import SyncClient, serve


def test_two_clients_collaborate(tmp_path):
    httpd = serve(port=0, data_dir=str(tmp_path))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        a = SyncClient(base, "note", "alice")
        b = SyncClient(base, "note", "bob")

        a.insert(0, "Hello from alice. ")
        a.sync()
        b.pull()
        assert b.text() == "Hello from alice. "

        # Concurrent edits.
        b.insert(len(b.text()), "And bob!")
        a.insert(0, ">> ")
        a.sync()
        b.sync()
        a.sync()
        assert a.text() == b.text()
        assert "And bob!" in a.text() and ">> " in a.text()

        # Server persisted a .dt file readable on its own.
        httpd.RequestHandlerClass.store.flush(force=True)
        from diamond_types_tpu.encoding.decode import load_oplog
        with open(tmp_path / "note.dt", "rb") as f:
            ol = load_oplog(f.read())
        assert ol.checkout_tip().snapshot() == a.text()
    finally:
        httpd.shutdown()
