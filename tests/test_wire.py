"""Wire-tier tests (diamond_types_tpu/wire/): envelope fuzzing
(truncation + bit flips must raise a framed decode error, never yield
garbage ops), payload codec round-trips for every frame type including
unicode-heavy op tapes, snapshot build/apply idempotence, and channel
negotiation/accounting. Pure host-side, tier-1 safe."""

import json
import random

import pytest

from diamond_types_tpu.replicate.metrics import ReplicationMetrics
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.wire.channel import WireChannel, wire_enabled
from diamond_types_tpu.wire.frames import (FRAME_DOCS, FRAME_OPS,
                                           FRAME_PATCH, FRAME_SNAPSHOT,
                                           FRAME_STATE, FRAME_SUMMARY,
                                           MAGIC, WIRE_CHANNELS,
                                           WIRE_KEYS, WireError,
                                           decode_docs, decode_frame,
                                           decode_ops, decode_records,
                                           decode_state, decode_summary,
                                           encode_docs, encode_frame,
                                           encode_ops, encode_records,
                                           encode_state, encode_summary,
                                           is_frame)
from diamond_types_tpu.wire.snapshot import (apply_snapshot,
                                             build_snapshot, missing_ops,
                                             should_ship_snapshot)

pytestmark = pytest.mark.wire

# astral plane, combining accent, CJK, latin-1 supplement — every op
# tape below draws from this so utf8 length != codepoint count
_ALPHABET = "etaoin shrdluéß世界\U0001f600é"


def _random_tape(rng, n_ops):
    """A plausible churn tape: interleaved unicode inserts and deletes
    against a tracked doc length (the shape the proxy channel ships)."""
    ops, doc_len = [], 0
    for _ in range(n_ops):
        if doc_len > 4 and rng.random() < 0.35:
            start = rng.randrange(doc_len)
            end = min(doc_len, start + 1 + rng.randrange(6))
            ops.append({"kind": "del", "start": start, "end": end})
            doc_len -= end - start
        else:
            text = "".join(rng.choice(_ALPHABET)
                           for _ in range(rng.randrange(1, 9)))
            pos = rng.randrange(doc_len + 1)
            ops.append({"kind": "ins", "pos": pos, "text": text})
            doc_len += len(text)
    return ops


def _random_req(rng, n_ops=12):
    agent = f"t{rng.randrange(3)}s{rng.randrange(9)}"
    return {"agent": agent,
            "version": [[agent, rng.randrange(1000)],
                        [f"peer{rng.randrange(4)}", rng.randrange(50)]],
            "ops": _random_tape(rng, n_ops)}


# ---- envelope --------------------------------------------------------------

def test_envelope_roundtrip_every_type():
    rng = random.Random(1)
    for ftype in (FRAME_SUMMARY, FRAME_PATCH, FRAME_OPS, FRAME_STATE,
                  FRAME_SNAPSHOT, FRAME_DOCS):
        for size in (0, 1, 63, 64, 65, 900):
            payload = bytes(rng.randrange(7) for _ in range(size))
            for compress in (False, True):
                frame = encode_frame(ftype, payload, compress=compress)
                assert is_frame(frame)
                assert decode_frame(frame) == (ftype, payload)


def test_envelope_compression_keeps_smaller_only():
    # low-entropy payload compresses; the frame must round-trip AND
    # actually come out smaller than the raw framing
    payload = b"abababab" * 200
    small = encode_frame(FRAME_PATCH, payload, compress=True)
    raw = encode_frame(FRAME_PATCH, payload, compress=False)
    assert len(small) < len(raw)
    assert decode_frame(small) == (FRAME_PATCH, payload)
    # tiny payloads are never compressed (the <=64 byte floor)
    tiny = encode_frame(FRAME_PATCH, b"ab" * 8, compress=True)
    assert decode_frame(tiny) == (FRAME_PATCH, b"ab" * 8)


def test_envelope_rejects_version_type_and_flags():
    frame = bytearray(encode_frame(FRAME_OPS, b"x" * 20))
    bad_version = bytes(frame[:4]) + b"\x02" + bytes(frame[5:])
    with pytest.raises(WireError):
        decode_frame(bad_version)
    bad_type = bytes(frame[:5]) + b"\x63" + bytes(frame[6:])
    with pytest.raises(WireError):
        decode_frame(bad_type)
    bad_flags = bytes(frame[:6]) + b"\x40" + bytes(frame[7:])
    with pytest.raises(WireError):
        decode_frame(bad_flags)
    with pytest.raises(WireError):
        decode_frame(b"JSON" + bytes(frame[4:]))   # not our magic
    with pytest.raises(WireError):
        decode_frame(MAGIC)                        # shorter than a header


def test_fuzz_truncation_always_raises():
    """Every strict prefix of a valid frame is a framed decode error —
    a cut-off transfer can never decode into ops."""
    rng = random.Random(2)
    for _ in range(8):
        req = _random_req(rng)
        frame = encode_frame(FRAME_OPS, encode_ops(req), compress=True)
        assert decode_ops(decode_frame(frame)[1]) == req
        for cut in range(len(frame)):
            with pytest.raises(WireError):
                decode_frame(frame[:cut])


def test_fuzz_bitflip_always_raises():
    """Flipping any single bit anywhere in a frame (magic, header,
    length, payload, crc) must surface as WireError: the crc catches
    payload damage, explicit checks catch header damage. Corruption
    never decodes into garbage ops."""
    rng = random.Random(3)
    for compress in (False, True):
        req = _random_req(rng, n_ops=20)
        frame = encode_frame(FRAME_OPS, encode_ops(req),
                             compress=compress)
        for i in range(len(frame)):
            mutated = bytearray(frame)
            mutated[i] ^= 1 << rng.randrange(8)
            with pytest.raises(WireError):
                decode_frame(bytes(mutated))


def test_fuzz_random_junk_never_decodes():
    rng = random.Random(4)
    for n in (0, 3, 11, 12, 40, 300):
        junk = bytes(rng.randrange(256) for _ in range(n))
        with pytest.raises(WireError):
            decode_frame(junk)
        with pytest.raises(WireError):
            decode_frame(MAGIC + junk)


# ---- payload codecs --------------------------------------------------------

def test_ops_tape_roundtrip_fuzz():
    """Random unicode op tapes round-trip exactly: decoded dict equals
    the input, and re-encoding is byte-identical (canonical form)."""
    rng = random.Random(5)
    for _ in range(40):
        req = _random_req(rng, n_ops=rng.randrange(0, 30))
        payload = encode_ops(req)
        out = decode_ops(payload)
        assert out == req
        assert encode_ops(out) == payload
    with pytest.raises(WireError):
        encode_ops({"agent": "a", "version": [],
                    "ops": [{"kind": "mv", "pos": 0}]})
    with pytest.raises(WireError):
        decode_ops(encode_ops(_random_req(rng)) + b"\x00")


def test_summary_roundtrip_and_wins_over_json():
    rng = random.Random(6)
    summary = {}
    for a in range(12):
        runs, prev = [], 0
        for _ in range(rng.randrange(1, 5)):
            s = prev + rng.randrange(0, 40)
            e = s + 1 + rng.randrange(200)
            runs.append([s, e])
            prev = e
        summary[f"tenant{a % 3}-sess{a}"] = runs
    payload = encode_summary(summary)
    assert decode_summary(payload) == summary
    assert len(payload) < len(json.dumps(summary).encode("utf8"))
    with pytest.raises(WireError):
        decode_summary(payload + b"\x01")


def test_state_roundtrip_unicode():
    text = "héllo 世界 \U0001f600" * 40
    version = [["alice", 7], ["bøb", 123456]]
    payload = encode_state(text, version)
    assert decode_state(payload) == (text, version)
    with pytest.raises(WireError):
        decode_state(payload + b"\x00")


def test_docs_roundtrip_with_leases_and_frontiers():
    listing = {
        "self": "127.0.0.1:9001",
        "docs": {
            "t0-doc001": {"lease": {"holder": "127.0.0.1:9002",
                                    "epoch": 4, "state": "active",
                                    "ttl_s": 0.9},
                          "frontier": [["alice", 10], ["bob", 3]]},
            "t0-doc002": {"lease": {"holder": "127.0.0.1:9002",
                                    "epoch": 9, "state": "granted",
                                    "ttl_s": 1.5},
                          "frontier": []},
            "t1-doc000": {"lease": None,
                          "frontier": [["céline", 2]]},
            "t1-doc001": {"lease": None},   # no frontier advertised
        },
    }
    out = decode_docs(encode_docs(listing))
    assert out["self"] == listing["self"]
    assert set(out["docs"]) == set(listing["docs"])
    d1 = out["docs"]["t0-doc001"]
    assert d1["lease"] == listing["docs"]["t0-doc001"]["lease"]
    assert d1["frontier"] == [["alice", 10], ["bob", 3]]
    assert out["docs"]["t1-doc000"]["lease"] is None
    assert "frontier" not in out["docs"]["t1-doc001"]
    # negative ttl clamps to zero rather than wrapping the varint
    neg = {"self": "s", "docs": {"d": {"lease": {
        "holder": "h", "epoch": 1, "state": "active", "ttl_s": -3.0}}}}
    assert decode_docs(encode_docs(neg))["docs"]["d"]["lease"]["ttl_s"] == 0.0


def test_docs_rejects_unknown_flags():
    # single doc, no lease, no frontier: the flags byte is last
    payload = bytearray(encode_docs({"self": "s", "docs": {"d": {}}}))
    assert payload[-1] == 0
    payload[-1] = 0x80
    with pytest.raises(WireError):
        decode_docs(bytes(payload))
    with pytest.raises(WireError):
        decode_docs(bytes(payload[:-1]))       # truncated doc entry


def test_records_roundtrip_and_truncation():
    records = [b"DMNDTYPS" + bytes(range(50)), b"", b"\x00" * 9]
    payload = encode_records(records)
    assert decode_records(payload) == records
    with pytest.raises(WireError):
        decode_records(payload[:-3])
    with pytest.raises(WireError):
        decode_records(payload + b"\x00")


# ---- snapshot shipping -----------------------------------------------------

def _seed_oplog(text="snapshot shipping"):
    ol = OpLog()
    a = ol.get_or_create_agent_id("alice")
    for i, ch in enumerate(text):
        ol.add_insert(a, i, ch)
    return ol


def test_snapshot_build_apply_idempotent():
    ol = _seed_oplog()
    frame = build_snapshot(ol)
    assert is_frame(frame)
    ol2 = OpLog()
    merged = apply_snapshot(ol2, frame)
    assert merged == len(ol)
    assert ol2.checkout_tip().snapshot() == ol.checkout_tip().snapshot()
    # double delivery merges to the same bytes (dedup-safe replay)
    assert apply_snapshot(ol2, frame) == 0
    assert ol2.checkout_tip().snapshot() == ol.checkout_tip().snapshot()


def test_apply_snapshot_rejects_wrong_frame_type():
    with pytest.raises(WireError):
        apply_snapshot(OpLog(), encode_frame(FRAME_PATCH, b"nope"))
    with pytest.raises(WireError):
        apply_snapshot(OpLog(), b"not a frame at all")


def test_should_ship_snapshot_threshold():
    ol = _seed_oplog("0123456789")
    assert missing_ops(ol.cg, ol.version, []) == len(ol)
    assert should_ship_snapshot(ol.cg, ol.version, [], threshold=4)
    assert not should_ship_snapshot(ol.cg, ol.version, [], threshold=10)
    assert not should_ship_snapshot(ol.cg, ol.version, [], threshold=0)
    # peer already at tip: nothing missing, never ship
    assert not should_ship_snapshot(ol.cg, ol.version, list(ol.version),
                                    threshold=1)


# ---- channel: negotiation, accounting, frame cache -------------------------

def test_channel_negotiation_and_fallback():
    ch = WireChannel(enabled=True)
    assert ch.header_value() == "v1"
    assert not ch.use_wire("peer")          # unknown peer: JSON fallback
    ch.note_peer("peer", 1)
    assert ch.use_wire("peer")
    ch.note_peer("old", None)               # pre-wire build gossips nothing
    assert not ch.use_wire("old")
    ch.note_peer("weird", "bogus")
    assert not ch.use_wire("weird")
    off = WireChannel(enabled=False)
    off.note_peer("peer", 1)
    assert off.header_value() is None and not off.use_wire("peer")


def test_wire_enabled_env_kill_switch(monkeypatch):
    monkeypatch.setenv("DT_WIRE_DISABLED", "1")
    assert not wire_enabled()
    assert not WireChannel().enabled        # default follows the env
    monkeypatch.setenv("DT_WIRE_DISABLED", "0")
    assert wire_enabled()
    monkeypatch.delenv("DT_WIRE_DISABLED")
    assert wire_enabled()


def test_channel_accounting_lands_in_metrics():
    m = ReplicationMetrics()
    ch = WireChannel(metrics=m, enabled=True)
    ch.account("proxy", sent_bytes=10, json_bytes=30, framed=True)
    ch.account("proxy", sent_bytes=50)      # JSON fallback: bytes only
    ch.account("hydrate", sent_bytes=5, framed=True, snapshot=True)
    # a frame that did NOT beat JSON never counts negative savings
    ch.account("antientropy", sent_bytes=40, json_bytes=40, framed=True)
    w = m.wire_counters()
    assert w["proxy_bytes_sent"] == 60
    assert w["proxy_bytes_saved"] == 20
    assert w["proxy_frames"] == 1
    assert w["hydrate_frames"] == 1
    assert w["hydrate_snapshot_ships"] == 1
    assert w["antientropy_bytes_saved"] == 0
    assert set(w) == {f"{c}_{k}" for c in WIRE_CHANNELS
                      for k in WIRE_KEYS}
    # the snapshot embeds the flat wire group for the scorecard
    assert m.snapshot()["wire"]["gossip_bytes_sent"] == 0
    # metricsless channel still answers counters() with zeros
    assert WireChannel().counters()["proxy_frames"] == 0


def test_frame_cache_reuse_invalidate_evict():
    ch = WireChannel(enabled=True, cache_entries=2)
    builds = []

    def builder(tag):
        def build():
            builds.append(tag)
            return f"frame:{tag}".encode("utf8")
        return build

    key = (("alice", 3),)
    assert ch.cached_snapshot("d1", key, builder("a")) == b"frame:a"
    assert ch.cached_snapshot("d1", key, builder("a2")) == b"frame:a"
    assert builds == ["a"]                  # second hit served cached
    ch.invalidate("d1")
    assert ch.cached_snapshot("d1", key, builder("a3")) == b"frame:a3"
    # eviction: cache holds 2 entries, the oldest falls out
    ch.cached_snapshot("d2", key, builder("b"))
    ch.cached_snapshot("d3", key, builder("c"))
    ch.cached_snapshot("d1", key, builder("a4"))
    assert builds == ["a", "a3", "b", "c", "a4"]
