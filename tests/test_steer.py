"""Shape steering + device-resident staging (PR 20 tentpole).

Covers, strictly above the parity fences:
  * `ShapeSteer.snap` policy — exact-warm hits, bounded-waste padding,
    forced first-sight pads, recurrence-gated compiles, the mesh batch
    multiple, and the disabled passthrough;
  * `cap_class` / `warmup_batches` — the single cap-floor source of
    truth shared by `warmup_fused_cache` and `_materialize`;
  * randomized mixed-bucket byte parity steered vs. unsteered vs. the
    host oracle across the ladder rungs (pallas / mesh / fused /
    per-doc), with explicit padded-window parity;
  * the warmup-then-steady pin: zero compiles and zero jit misses on
    a steered drifting tape after `warmup_fused_cache`;
  * window-arena donated-buffer reuse — the fast path engages on a
    recurring window, and a poisoned row mid-window can never leak a
    stale arena slot (ladder fallback semantics intact);
  * host->device transfer accounting split by (rung, purpose) and the
    zero-filled prom families.

Runs on the CPU-simulated mesh (conftest pins JAX_PLATFORMS=cpu and
an 8-device virtual host platform).
"""

import random

import numpy as np
import pytest

from diamond_types_tpu.obs.devprof import PROFILER
from diamond_types_tpu.parallel import arena
from diamond_types_tpu.parallel import mesh as pm
from diamond_types_tpu.text.oplog import OpLog
from diamond_types_tpu.tpu import flush_fuse as ff
from diamond_types_tpu.tpu.steer import (STEER, ShapeSteer, cap_class,
                                         warmup_batches)

pytestmark = [pytest.mark.fused, pytest.mark.serve]

FUSED_OPTS = {"cap": 256, "max_ins": 4}
MI, CAP = 4, 256


@pytest.fixture(autouse=True)
def _steer_clean():
    """Steering/arena state is process-global: start every test from a
    cold table + empty arenas and restore the default switches."""
    STEER.reset(table=True)
    STEER.enabled = True
    arena.DEVICE_STAGE.enabled = True
    arena.reset_arenas()
    yield
    STEER.reset(table=True)
    STEER.enabled = True
    arena.DEVICE_STAGE.enabled = True
    arena.reset_arenas()
    PROFILER.enabled = False
    PROFILER.reset()


def _mk_oplog(doc_id: str) -> OpLog:
    ol = OpLog()
    ol.doc_id = doc_id
    return ol


def _random_edits(ol: OpLog, rng: random.Random, n: int,
                  agent: str = "a") -> None:
    a = ol.get_or_create_agent_id(agent)
    for _ in range(n):
        cur = len(ol.checkout_tip().snapshot())
        if cur and rng.random() < 0.3:
            pos = rng.randrange(cur)
            end = min(pos + rng.randint(1, 6), cur)
            ol.add_delete_without_content(a, pos, end)
        else:
            pos = rng.randint(0, cur)
            s = "".join(rng.choice("abcdef") for _ in
                        range(rng.randint(1, 5)))
            ol.add_insert(a, pos, s)


# ---- snap policy ---------------------------------------------------------

def test_snap_exact_warm_hit():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 2, 8)
    assert s.snap("fused", 2, 8, MI, CAP) == (2, 8)
    snap = s.snapshot()
    assert snap["hits"] == 1 and snap["compiles"] == 0
    assert snap["hit_rate"] == 1.0


def test_snap_pads_to_cheapest_inbound_class():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 4, 16)   # 64 cells
    s.note_warm("fused", MI, CAP, 8, 64)   # 512 cells
    # floor (2, 8) = 16 cells: both classes cover it, (4, 16) is the
    # cheapest and sits inside max_waste (64 <= 4 * 16)
    assert s.snap("fused", 2, 8, MI, CAP) == (4, 16)
    assert s.snapshot()["padded"] == 1


def test_snap_waste_bound_forced_pad_then_compile():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 16, 64)   # 1024 cells
    # floor (1, 2) = 2 cells: the only warm neighbor blows max_waste
    # (1024 > 4 * 2). First sight borrows it anyway — padding waste
    # beats a request-path compile for a one-off shape...
    assert s.snap("fused", 1, 2, MI, CAP) == (16, 64)
    snap = s.snapshot()
    assert snap["forced_pads"] == 1 and snap["compiles"] == 0
    # ...but a RECURRING shape earns its own class
    assert s.snap("fused", 1, 2, MI, CAP) == (1, 2)
    assert s.snapshot()["compiles"] == 1
    # once the compile lands in the real cache, note_warm makes it hit
    s.note_warm("fused", MI, CAP, 1, 2)
    assert s.snap("fused", 1, 2, MI, CAP) == (1, 2)
    assert s.snapshot()["hits"] == 1


def test_snap_no_candidate_compiles_immediately():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 2, 8)
    # bw=2 < bp0=4: no warm class covers the batch — exact class, no
    # recurrence wait (there is nothing to borrow)
    assert s.snap("fused", 4, 8, MI, CAP) == (4, 8)
    assert s.snapshot()["compiles"] == 1


def test_snap_respects_mesh_batch_multiple():
    s = ShapeSteer()
    s.note_warm("mesh", MI, CAP, 2, 32)    # not divisible by 4
    s.note_warm("mesh", MI, CAP, 4, 8)
    assert s.snap("mesh", 2, 8, MI, CAP, multiple=4) == (4, 8)


def test_snap_keys_isolate_cache_mi_cap():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 4, 8)
    # other cache / other cap: the warm class must not cross-match
    assert s.snap("mesh", 4, 8, MI, CAP) == (4, 8)
    assert s.snap("fused", 4, 8, MI, 512) == (4, 8)
    assert s.snapshot()["compiles"] == 2


def test_snap_disabled_is_passthrough():
    s = ShapeSteer(enabled=False)
    s.note_warm("fused", MI, CAP, 8, 8)
    assert s.snap("fused", 2, 2, MI, CAP) == (2, 2)
    assert s.snapshot()["lookups"] == 0


def test_reset_counts_vs_table():
    s = ShapeSteer()
    s.note_warm("fused", MI, CAP, 2, 8)
    s.snap("fused", 2, 8, MI, CAP)
    s.reset()
    assert s.snapshot()["lookups"] == 0
    assert s.snapshot()["warm_classes"] == {"fused": 1}
    s.reset(table=True)
    assert s.snapshot()["warm_classes"] == {}


# ---- cap-floor agreement (the warmup drift fix) --------------------------

def test_cap_class_floor_and_pow2():
    assert cap_class(1) == 256
    assert cap_class(256) == 256
    assert cap_class(300) == 512
    assert cap_class(5000) == 8192


def test_warmup_batches_enumeration():
    assert warmup_batches(1) == [1]
    assert warmup_batches(8) == [1, 2, 4, 8]
    assert warmup_batches(6) == [1, 2, 4, 8]


def test_session_materializes_on_cap_class():
    """A fresh session lands exactly on `cap_class` — the class warmup
    enumerates — so warmed kernels are the kernels flushes hit."""
    ol = _mk_oplog("d0")
    a = ol.get_or_create_agent_id("a")
    ol.add_insert(a, 0, "x" * 200)
    s = ff.FusedDocSession(ol, **FUSED_OPTS)
    assert s.cap == cap_class(int(200 * s.headroom))
    assert s.cap == cap_class(s.cap)


# ---- steered byte parity across the rungs --------------------------------

def _replay(rung, mesh, sess, plans):
    if rung == "mesh":
        ok, _dev, _bp, _staged = pm.mesh_fused_replay(mesh, sess, plans)
        return ok
    if rung == "pallas":
        ok, _dev = ff.pallas_fused_replay(sess, plans)
        return ok
    ok, _dev = ff.fused_replay(sess, plans)
    return ok


@pytest.mark.parametrize("rung", ["fused", "pallas", "mesh"])
def test_steered_vs_unsteered_vs_host_randomized_parity(rung):
    """Randomized mixed buckets re-windowed across rounds: the steered
    arm, the unsteered arm, and the host oracle stay byte-identical on
    every rung. Steering only changes the PADDED shape dispatched —
    inert pad rows by construction — so parity must be exact."""
    mesh = pm.serve_mesh(4) if rung == "mesh" else None
    rng_s = random.Random(23)
    rng_u = random.Random(23)
    ols_s = [_mk_oplog(f"d{i}") for i in range(5)]
    ols_u = [_mk_oplog(f"d{i}") for i in range(5)]
    for i, (a, b) in enumerate(zip(ols_s, ols_u)):
        _random_edits(a, rng_s, 2 + i)
        _random_edits(b, rng_u, 2 + i)
    sess_s = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols_s]
    sess_u = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols_u]
    for rnd in range(3):
        for i, (a, b) in enumerate(zip(ols_s, ols_u)):
            _random_edits(a, rng_s, 1 + (i + rnd) % 3)
            _random_edits(b, rng_u, 1 + (i + rnd) % 3)
        # drifting window width: rounds dispatch 5 then 3 then 5 docs
        k = 3 if rnd == 1 else 5
        STEER.enabled = True
        ok = _replay(rung, mesh, sess_s[:k],
                     [s.plan_tail() for s in sess_s[:k]])
        assert all(ok)
        STEER.enabled = False
        ok = _replay(rung, mesh, sess_u[:k],
                     [s.plan_tail() for s in sess_u[:k]])
        assert all(ok)
        for s, u, ol in zip(sess_s[:k], sess_u[:k], ols_s[:k]):
            want = ol.checkout_tip().snapshot()
            assert s.text() == want
            assert u.text() == want
    assert STEER.snapshot()["lookups"] >= 3


def test_perdoc_and_host_rungs_unaffected_by_steering():
    """The per-doc rung (batch 1, `sync()`) and the host oracle below
    it ride the same steer table: parity pinned with the table warm."""
    STEER.note_warm("fused", MI, CAP, 8, 8)
    rng = random.Random(5)
    ol = _mk_oplog("d0")
    _random_edits(ol, rng, 4)
    s = ff.FusedDocSession(ol, **FUSED_OPTS)
    for _ in range(3):
        _random_edits(ol, rng, 2)
        s.sync()
        assert s.text() == ol.checkout_tip().snapshot()


def test_explicitly_padded_window_byte_parity():
    """Force the pad-up path: a strictly larger in-bound warm class
    absorbs the window and the result is still byte-identical."""
    STEER.note_warm("fused", MI, CAP, 8, 4)    # 32 cells, in-bound
    rng = random.Random(9)
    ols = [_mk_oplog(f"d{i}") for i in range(3)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for ol in ols:
        _random_edits(ol, rng, 1)
    plans = [s.plan_tail() for s in sess]
    ok, _dev = ff.fused_replay(sess, plans)
    assert all(ok)
    assert STEER.snapshot()["padded"] >= 1
    for s, ol in zip(sess, ols):
        assert s.text() == ol.checkout_tip().snapshot()


# ---- warmup-then-steady: the zero-compiles pin ---------------------------

def test_warmup_then_steady_zero_compiles():
    """After `warmup_fused_cache`, a steered steady-state tape whose
    floors drift inside the warmed envelope triggers ZERO jit-cache
    misses and ZERO steer compiles — every window lands on a warm
    class, the acceptance pin behind the >= 90% hit-rate claim."""
    ff.warmup_fused_cache(flush_docs=4, cap=CAP, max_ins=MI,
                          mesh_shards=2)
    mesh = pm.serve_mesh(2)
    STEER.reset()                      # counters only; table stays warm
    PROFILER.reset()
    PROFILER.enabled = True
    rng = random.Random(31)
    ols = [_mk_oplog(f"d{i}") for i in range(4)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for rnd in range(6):
        k = 1 + (rnd % 4)              # drifting window width 1..4
        for ol in ols[:k]:
            _random_edits(ol, rng, 1 + rnd % 2)
        plans = [s.plan_tail() for s in sess[:k]]
        if rnd % 2:
            ok, _d, _bp, _st = pm.mesh_fused_replay(mesh, sess[:k],
                                                    plans)
        else:
            ok, _d = ff.fused_replay(sess[:k], plans)
        assert all(ok)
        for s, ol in zip(sess[:k], ols[:k]):
            assert s.text() == ol.checkout_tip().snapshot()
    snap = STEER.snapshot()
    assert snap["compiles"] == 0, snap
    assert snap["hit_rate"] == 1.0, snap
    jit = PROFILER.snapshot()["jit_cache"]
    for cache in ("fused", "mesh"):
        assert jit.get(cache, {}).get("misses", 0) == 0, jit


# ---- window arena: donated-buffer reuse ----------------------------------

def _spy_acquire(monkeypatch):
    hits = []
    orig = arena.acquire

    def spy(*a, **k):
        r = orig(*a, **k)
        hits.append(r is not None)
        return r

    monkeypatch.setattr(arena, "acquire", spy)
    return hits


def test_arena_fast_path_engages_on_recurring_window(monkeypatch):
    """Window k's donated outputs become window k+1's inputs when the
    same session list recurs in the same shape class — and parity
    against the host oracle holds through the handoff."""
    hits = _spy_acquire(monkeypatch)
    mesh = pm.serve_mesh(2)
    rng = random.Random(41)
    ols = [_mk_oplog(f"d{i}") for i in range(4)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    for rnd in range(3):
        for ol in ols:
            _random_edits(ol, rng, 2)
        plans = [s.plan_tail() for s in sess]
        ok, _d, _bp, _st = pm.mesh_fused_replay(mesh, sess, plans)
        assert all(ok)
        for s, ol in zip(sess, ols):
            assert s.text() == ol.checkout_tip().snapshot()
    # first window gathers (nothing parked), every recurrence reuses
    assert hits == [False, True, True]
    st = arena.arena_stats()
    assert st["arenas"] == 1 and st["generations"] == 3


def test_arena_poisoned_row_cannot_leak_stale_slot(monkeypatch):
    """Ladder-fallback mid-window: a row that fails the adopt_results
    length fence is left untagged, so the NEXT window's fast path
    misses and rebuilds from the sessions' own rows — the poisoned
    slot's stale bytes are unreachable by construction."""
    hits = _spy_acquire(monkeypatch)
    mesh = pm.serve_mesh(2)
    rng = random.Random(43)
    ols = [_mk_oplog(f"d{i}") for i in range(4)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    # window 1: clean — arena parked, all rows tagged
    for ol in ols:
        _random_edits(ol, rng, 2)
    ok, _d, _bp, _st = pm.mesh_fused_replay(
        mesh, sess, [s.plan_tail() for s in sess])
    assert all(ok)
    # window 2: doc 2's plan projection is tampered -> its returned
    # length fails the fence -> NOT committed, NOT re-tagged
    for ol in ols:
        _random_edits(ol, rng, 2)
    pre_text = sess[2].text()       # state BEFORE window 2's commit
    plans = [s.plan_tail() for s in sess]
    plans[2].new_len += 1
    ok, _d, _bp, _st = pm.mesh_fused_replay(mesh, sess, plans)
    assert ok == [True, True, False, True]
    assert sess[2].text() == pre_text          # kept pre-window state
    assert getattr(sess[2], "_arena_tag", None) is None
    assert getattr(sess[0], "_arena_tag", None) is not None
    # window 3: untainted plans. The fast path MUST miss (doc 2's tag
    # is gone) and the gather path replays doc 2's full pending tail
    for ol in ols:
        _random_edits(ol, rng, 1)
    ok, _d, _bp, _st = pm.mesh_fused_replay(
        mesh, sess, [s.plan_tail() for s in sess])
    assert all(ok)
    assert hits == [False, True, False]
    for s, ol in zip(sess, ols):
        assert s.text() == ol.checkout_tip().snapshot()


def test_session_mutation_clears_arena_tag():
    """Any out-of-window rebuild (`_materialize`) invalidates the
    session's arena slot — the fast path can never replay over it."""
    mesh = pm.serve_mesh(2)
    rng = random.Random(47)
    ols = [_mk_oplog(f"d{i}") for i in range(2)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    ok, _d, _bp, _st = pm.mesh_fused_replay(
        mesh, sess, [s.plan_tail() for s in sess])
    assert all(ok)
    assert getattr(sess[0], "_arena_tag", None) is not None
    sess[0]._materialize()
    assert sess[0]._arena_tag is None
    assert arena.acquire(mesh, sess[0].cap, MI, sess, 2) is None


def test_device_stage_off_is_host_control_arm(monkeypatch):
    """`DEVICE_STAGE` disabled: the arena never engages and every
    resident state byte is re-staged through host numpy (the A/B
    control) — with byte parity unchanged."""
    hits = _spy_acquire(monkeypatch)
    arena.DEVICE_STAGE.enabled = False
    mesh = pm.serve_mesh(2)
    rng = random.Random(53)
    ols = [_mk_oplog(f"d{i}") for i in range(3)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    staged = []
    for rnd in range(2):
        for ol in ols:
            _random_edits(ol, rng, 2)
        ok, _d, bp, st = pm.mesh_fused_replay(
            mesh, sess, [s.plan_tail() for s in sess])
        assert all(ok)
        staged.append((bp, st))
        for s, ol in zip(sess, ols):
            assert s.text() == ol.checkout_tip().snapshot()
    assert hits == []                   # fast path never consulted
    assert arena.arena_stats()["arenas"] == 0
    # control staging pays the full [bp, cap] state each window
    for bp, st in staged:
        assert st > bp * CAP * 4


# ---- transfer accounting: the (rung, purpose) split ----------------------

def test_transfer_accounting_split_by_rung_and_purpose():
    PROFILER.reset()
    PROFILER.enabled = True
    mesh = pm.serve_mesh(2)
    rng = random.Random(59)
    ols = [_mk_oplog(f"d{i}") for i in range(3)]
    for ol in ols:
        _random_edits(ol, rng, 2)
    sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
    detail = PROFILER.snapshot()["transfer_detail"]
    assert detail["session.stage"]["transfers"] == 3   # materialize
    # device-resident staging: the mesh window pays PLAN bytes only
    for ol in ols:
        _random_edits(ol, rng, 1)
    ok, _d, _bp, staged = pm.mesh_fused_replay(
        mesh, sess, [s.plan_tail() for s in sess])
    assert all(ok)
    detail = PROFILER.snapshot()["transfer_detail"]
    assert detail["mesh.plan"]["bytes"] == staged
    assert "mesh.stage" not in detail
    # control arm: state bytes appear under mesh.stage and dominate
    arena.DEVICE_STAGE.enabled = False
    plan_before = detail["mesh.plan"]["bytes"]
    for ol in ols:
        _random_edits(ol, rng, 1)
    ok, _d, bp, staged = pm.mesh_fused_replay(
        mesh, sess, [s.plan_tail() for s in sess])
    assert all(ok)
    detail = PROFILER.snapshot()["transfer_detail"]
    assert detail["mesh.stage"]["bytes"] == bp * CAP * 4 + bp * 4
    assert staged == detail["mesh.stage"]["bytes"] \
        + (detail["mesh.plan"]["bytes"] - plan_before)
    # per-shard rungs tag their plan uploads too
    arena.DEVICE_STAGE.enabled = True
    for ol in ols:
        _random_edits(ol, rng, 1)
    ok, _d = ff.fused_replay(sess, [s.plan_tail() for s in sess])
    assert all(ok)
    assert "fused.plan" in PROFILER.snapshot()["transfer_detail"]


def test_warmup_transfers_tagged_and_staged_reduction():
    """Mesh warmup uploads are purpose="warmup" (kept out of the
    steady-state staging claim), and the device arm's per-window
    staging is <= half the host control arm's on the same window."""
    PROFILER.reset()
    PROFILER.enabled = True
    ff.warmup_fused_cache(flush_docs=2, cap=CAP, max_ins=MI,
                          mesh_shards=2)
    detail = PROFILER.snapshot()["transfer_detail"]
    assert detail["mesh.warmup"]["bytes"] > 0
    mesh = pm.serve_mesh(2)
    rng = random.Random(61)

    def _window(device_stage):
        arena.DEVICE_STAGE.enabled = device_stage
        arena.reset_arenas()
        ols = [_mk_oplog(f"d{i}") for i in range(3)]
        for ol in ols:
            _random_edits(ol, rng, 2)
        sess = [ff.FusedDocSession(ol, **FUSED_OPTS) for ol in ols]
        for ol in ols:
            _random_edits(ol, rng, 1)
        ok, _d, _bp, staged = pm.mesh_fused_replay(
            mesh, sess, [s.plan_tail() for s in sess])
        assert all(ok)
        return staged

    staged_dev = _window(True)
    staged_host = _window(False)
    assert staged_dev <= staged_host / 2, (staged_dev, staged_host)


def test_prom_families_zero_filled():
    """The staging + hit-rate prom families exist from the first
    scrape (zero-filled), not only after the first window."""
    from diamond_types_tpu.obs.prom import render_metrics
    from diamond_types_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics(2, 4, 64)
    m.record_window(1, 2, 2)            # no staged bytes yet
    text = render_metrics({"serve": m.snapshot(),
                           "obs": {"devprof": {"jit_cache": {}}}})
    assert "dt_serve_window_transfer_bytes_total 0" in text
    assert "dt_serve_window_staged_bytes_per_window 0.0" in text
    assert 'dt_devprof_jit_hit_rate{cache="mesh"} 0.0' in text
    m.record_window(1, 2, 2, staged_bytes=4096)
    text = render_metrics({
        "serve": m.snapshot(),
        "obs": {"devprof": {
            "jit_cache": {"mesh": {"hits": 3, "misses": 1}},
            "transfer_detail": {"mesh.plan": {"transfers": 2,
                                              "bytes": 512}}}}})
    assert "dt_serve_window_transfer_bytes_total 4096" in text
    assert 'dt_devprof_jit_hit_rate{cache="mesh"} 0.75' in text
    assert ('dt_devprof_transfer_detail_bytes_total'
            '{purpose="plan",rung="mesh"} 512') in text


def test_scorecard_serve_block_bands_and_missing_skip():
    """The serve.* bands gate when both cards carry the block and are
    skipped (never gate) against a host-engine card without it."""
    from diamond_types_tpu.obs.scorecard import (build_scorecard,
                                                 diff_scorecards)

    def _card(serve):
        return build_scorecard(
            scenario={"name": "t"}, wall_s=1.0, virtual_s=0.0,
            totals={"ops": 10}, latency_p99_s={"flush": 0.01},
            slo={"slo_ok": True}, ok=True, serve=serve)

    old = _card({"jit_cache_hit_rate": 0.95,
                 "staged_bytes_per_window": 4000.0,
                 "device_calls_per_window": 1.0})
    good = _card({"jit_cache_hit_rate": 0.97,
                  "staged_bytes_per_window": 3500.0,
                  "device_calls_per_window": 1.0})
    bad = _card({"jit_cache_hit_rate": 0.60,
                 "staged_bytes_per_window": 4000.0,
                 "device_calls_per_window": 1.0})
    assert diff_scorecards(old, good)["ok"]
    d = diff_scorecards(old, bad)
    assert not d["ok"]
    assert "serve.jit_cache_hit_rate" in d["regressions"]
    hostcard = _card(None)
    d = diff_scorecards(hostcard, good)
    assert d["ok"]
    assert "serve.jit_cache_hit_rate" in d["skipped"]
