"""Generate the browser-CRDT golden conformance fixture.

Produces tests/data/crdt_client_golden.json — op streams (unit ops with
explicit parents, covering concurrent same-gap inserts, doc-end ties,
same-agent concurrency and the scanning-rollback shapes) with expected
final texts computed by the ORACLE engine (the real oplog via the server
protocol) — and tests/data/crdt_conformance.mjs, a standalone node
runner embedding the EXACT shipped JS engine (web_assets.crdt_engine_js)
so the vectors are executable against the real JS wherever a JS runtime
exists. The fixture records the engine source's sha256; the test suite
fails if the shipped JS drifts from what the fixture was generated from
(VERDICT r3 missing #3: mirror drift was structurally undetectable).

Regenerate after any engine edit:  python -m tests.gen_crdt_golden
"""

import hashlib
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
                + "/tests")

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

ALPHABET = "abcdefgh XY12©Δ←\U00010190"


def handcrafted_vectors():
    """Directed cases for the YjsMod edges (zone-engine memory: left
    spine, doc-end ties, same-agent concurrency, scanning rollback)."""
    vs = []

    # 1. concurrent same-gap inserts at pos 0 (agent tie-break)
    ops = []
    for i, ch in enumerate("AB"):
        ops.append({"agent": "anna", "seq": i,
                    "parents": [["anna", i - 1]] if i else [],
                    "kind": "ins", "pos": i, "ch": ch})
    for i, ch in enumerate("XY"):
        ops.append({"agent": "bert", "seq": i,
                    "parents": [["bert", i - 1]] if i else [],
                    "kind": "ins", "pos": i, "ch": ch})
    vs.append(("concurrent_gap0", ops))

    # 2. doc-end tie: both agents append at the end of a shared doc
    ops = []
    for i, ch in enumerate("abc"):
        ops.append({"agent": "anna", "seq": i,
                    "parents": [["anna", i - 1]] if i else [],
                    "kind": "ins", "pos": i, "ch": ch})
    base = [["anna", 2]]
    ops.append({"agent": "anna", "seq": 3, "parents": base,
                "kind": "ins", "pos": 3, "ch": "P"})
    ops.append({"agent": "bert", "seq": 0, "parents": base,
                "kind": "ins", "pos": 3, "ch": "Q"})
    vs.append(("doc_end_tie", ops))

    # 3. same-agent concurrency (git-import class: one author on
    # parallel branches — seq order does NOT imply causal order)
    ops = [
        {"agent": "solo", "seq": 0, "parents": [],
         "kind": "ins", "pos": 0, "ch": "L"},
        {"agent": "solo", "seq": 1, "parents": [],
         "kind": "ins", "pos": 0, "ch": "R"},
        {"agent": "solo", "seq": 2, "parents": [["solo", 0], ["solo", 1]],
         "kind": "ins", "pos": 1, "ch": "M"},
    ]
    vs.append(("same_agent_concurrent", ops))

    # 4. scanning shape: three agents insert runs into one gap with
    # differing right origins (the rollback-before-streak case)
    ops = []
    for i, ch in enumerate("ab"):
        ops.append({"agent": "base", "seq": i,
                    "parents": [["base", i - 1]] if i else [],
                    "kind": "ins", "pos": i, "ch": ch})
    gap = [["base", 1]]
    for agent, chars in (("p1", "12"), ("p2", "34"), ("p3", "56")):
        f = gap
        for i, ch in enumerate(chars):
            ops.append({"agent": agent, "seq": i, "parents": f,
                        "kind": "ins", "pos": 1 + i, "ch": ch})
            f = [[agent, i]]
    vs.append(("three_way_gap_runs", ops))

    # 5. delete/insert interleave across merges
    ops = [
        {"agent": "anna", "seq": 0, "parents": [],
         "kind": "ins", "pos": 0, "ch": "x"},
        {"agent": "anna", "seq": 1, "parents": [["anna", 0]],
         "kind": "ins", "pos": 1, "ch": "y"},
        {"agent": "bert", "seq": 0, "parents": [["anna", 1]],
         "kind": "del", "pos": 0, "ch": None},
        {"agent": "anna", "seq": 2, "parents": [["anna", 1]],
         "kind": "ins", "pos": 1, "ch": "z"},   # concurrent w/ the delete
    ]
    vs.append(("del_vs_ins_concurrent", ops))
    return vs


def fuzz_vector(seed, steps=40):
    """One random 3-peer unit-op history (same move set as the mirror
    fuzz, plus same-agent branch resets)."""
    from test_crdt_client_logic import _replay_mirror
    rng = random.Random(seed)
    agents = ["anna", "bert", "cleo"]
    ops = []
    heads = {a: ([], "") for a in agents}
    snapshots = {a: [] for a in agents}   # (frontier, text) history
    parented = set()   # (agent, seq) pairs referenced as a parent
    for _ in range(steps):
        a = agents[rng.randrange(3)]
        frontier, text = heads[a]
        seq = sum(1 for o in ops if o["agent"] == a)
        if not text or rng.random() < 0.65:
            pos = rng.randint(0, len(text))
            ch = rng.choice(ALPHABET)
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "ins", "pos": pos, "ch": ch})
            text = text[:pos] + ch + text[pos:]
        else:
            pos = rng.randrange(len(text))
            ops.append({"agent": a, "seq": seq, "parents": frontier,
                        "kind": "del", "pos": pos, "ch": None})
            text = text[:pos] + text[pos + 1:]
        parented.update((x, s) for (x, s) in frontier)
        heads[a] = ([[a, seq]], text)
        snapshots[a].append(heads[a])
        r = rng.random()
        if r < 0.25:
            # pull EVERYTHING: the frontier is the true maximal-op set —
            # with same-agent branch jumps, per-agent max seq is NOT a
            # covering frontier (seq order is not causal order)
            f = [[o["agent"], o["seq"]] for o in ops
                 if (o["agent"], o["seq"]) not in parented]
            heads[a] = (f, _replay_mirror(ops))
        elif r < 0.33 and len(snapshots[a]) > 2:
            # same-agent concurrency: jump back to an own old branch
            heads[a] = snapshots[a][rng.randrange(len(snapshots[a]) - 1)]
    return ops


MJS_TEMPLATE = '''// AUTO-GENERATED by tests/gen_crdt_golden.py — do not edit.
// Standalone conformance runner for the in-browser CRDT engine: embeds
// the EXACT engine shipped in web_assets.CRDT_HTML (itself GENERATED
// from tools/crdt_replay_src.py — the single source the Python suites
// execute) and replays the golden vectors from crdt_client_golden.json.
// Run with node:
//    node crdt_conformance.mjs
import {{ readFileSync }} from "fs";
import {{ dirname, join }} from "path";
import {{ fileURLToPath }} from "url";

{engine}

const fixture = JSON.parse(readFileSync(
  join(dirname(fileURLToPath(import.meta.url)), "crdt_client_golden.json"),
  "utf8"));
let fail = 0;
for (const v of fixture.vectors) {{
  const got = replay(v.ops);
  if (got !== v.expect) {{
    fail++;
    console.error(`FAIL ${{v.name}}: got ${{JSON.stringify(got)}} ` +
                  `want ${{JSON.stringify(v.expect)}}`);
  }}
}}
if (fail) {{ console.error(`${{fail}} vector(s) failed`); process.exit(1); }}
console.log(`${{fixture.vectors.length}} vectors OK`);
'''


def main():
    from diamond_types_tpu.tools.web_assets import crdt_engine_js
    from test_crdt_client_logic import _oracle_text, _replay_mirror

    vectors = []
    for name, ops in handcrafted_vectors():
        vectors.append({"name": name, "ops": ops,
                        "expect": _oracle_text(ops)})
    for seed in range(40):
        ops = fuzz_vector(7000 + seed)
        vectors.append({"name": f"fuzz_{seed}", "ops": ops,
                        "expect": _oracle_text(ops)})

    # the mirror must agree BEFORE we bless the fixture
    for v in vectors:
        got = _replay_mirror(v["ops"])
        assert got == v["expect"], \
            f"mirror disagrees with oracle on {v['name']}: " \
            f"{got!r} != {v['expect']!r}"

    import inspect

    from diamond_types_tpu.tools import crdt_replay_src
    engine = crdt_engine_js()
    src_text = inspect.getsource(crdt_replay_src)
    fixture = {
        "src_sha256": hashlib.sha256(src_text.encode("utf8")).hexdigest(),
        "generator": "tests/gen_crdt_golden.py",
        "vectors": vectors,
    }
    os.makedirs(DATA_DIR, exist_ok=True)
    path = os.path.join(DATA_DIR, "crdt_client_golden.json")
    with open(path, "w") as f:
        json.dump(fixture, f, indent=1, ensure_ascii=True)
    mjs = MJS_TEMPLATE.format(engine=engine)
    with open(os.path.join(DATA_DIR, "crdt_conformance.mjs"), "w") as f:
        f.write(mjs)
    print(f"wrote {len(vectors)} vectors to {path}")


if __name__ == "__main__":
    main()
