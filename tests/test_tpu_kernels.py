"""Device-tier tests on the virtual 8-device CPU mesh (conftest sets
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8)."""

import json
import os

import numpy as np
import pytest

from diamond_types_tpu.causalgraph.graph import Graph
from tests.conftest import reference_path

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from diamond_types_tpu.tpu import graph_kernels as gk  # noqa: E402
from diamond_types_tpu.tpu.batch import (docs_to_strings, encode_trace_ops,  # noqa: E402
                                         replay_batch)


def build_graph(hist):
    g = Graph()
    for e in hist:
        g.push(e["parents"], e["span"][0], e["span"][1])
    return g


def load_cases(name):
    path = os.path.join(reference_path("test_data", "causal_graph"), name)
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_device_contains_matches_golden_vectors():
    cases = load_cases("version_contains.json")
    # Group by identical graph to batch queries.
    by_hist = {}
    for c in cases:
        by_hist.setdefault(json.dumps(c["hist"]), []).append(c)
    for hist_s, group in by_hist.items():
        g = build_graph(json.loads(hist_s))
        fn = gk.make_contains_fn(g)
        k = max(len(c["frontier"]) for c in group) or 1
        frontiers = np.full((len(group), k), -1, dtype=np.int32)
        targets = np.zeros((len(group),), dtype=np.int32)
        for i, c in enumerate(group):
            for j, v in enumerate(c["frontier"]):
                frontiers[i, j] = v
            targets[i] = c["target"] if c["target"] != -1 else -1
        got = np.asarray(fn(jnp.asarray(frontiers), jnp.asarray(targets)))
        for i, c in enumerate(group):
            assert bool(got[i]) == c["expected"], (c, bool(got[i]))


def test_device_diff_matches_host():
    cases = load_cases("diff.json")
    for c in cases:
        g = build_graph(c["hist"])
        packed = gk.pack_graph(g)
        k = max(len(c["a"]), len(c["b"]), 1)

        def pad(f):
            return jnp.asarray(np.array(f + [-1] * (k - len(f)), dtype=np.int32))

        ra, rb = gk.diff_masks(packed, pad(list(c["a"])), pad(list(c["b"])))
        ra, rb = np.asarray(ra), np.asarray(rb)
        # only_a = covered by a but not b, per run
        only_a, only_b = [], []
        for i in range(len(g.starts)):
            s = g.starts[i]
            a_hi, b_hi = int(ra[i]), int(rb[i])
            if a_hi > b_hi:
                lo = max(s, b_hi + 1)
                if only_a and only_a[-1][1] == lo:
                    only_a[-1] = (only_a[-1][0], a_hi + 1)
                else:
                    only_a.append((lo, a_hi + 1))
            elif b_hi > a_hi:
                lo = max(s, a_hi + 1)
                if only_b and only_b[-1][1] == lo:
                    only_b[-1] = (only_b[-1][0], b_hi + 1)
                else:
                    only_b.append((lo, b_hi + 1))
        ea, eb = g.diff(c["a"], c["b"])
        assert only_a == ea, (c, only_a, ea)
        assert only_b == eb


def test_batched_replay_matches_rope():
    from diamond_types_tpu.text.trace import TestData, replay_direct
    txns = [[(0, 0, "hello world")], [(5, 6, "")], [(5, 0, ", there")],
            [(0, 1, "H")], [(12, 0, "!")]]
    data = TestData("", "", txns)
    expected = replay_direct(data)

    pos, dl, il, chars = encode_trace_ops(txns, max_ins=16)
    b = 8
    docs, lens = replay_batch(
        jnp.asarray(np.tile(pos, (b, 1))), jnp.asarray(np.tile(dl, (b, 1))),
        jnp.asarray(np.tile(il, (b, 1))),
        jnp.asarray(np.tile(chars, (b, 1, 1))), cap=64)
    out = docs_to_strings(np.asarray(docs), np.asarray(lens))
    assert all(s == expected for s in out)


def test_sharded_replay_8_devices():
    from diamond_types_tpu.parallel.mesh import make_mesh, sharded_replay
    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    mesh = make_mesh(8)
    txns = [[(0, 0, "abcdef")], [(2, 2, "XY")], [(0, 1, "")]]
    pos, dl, il, chars = encode_trace_ops(txns, max_ins=8)
    b = 16
    docs, lens = sharded_replay(
        mesh, np.tile(pos, (b, 1)), np.tile(dl, (b, 1)),
        np.tile(il, (b, 1)), np.tile(chars, (b, 1, 1)), cap=32)
    out = docs_to_strings(np.asarray(docs), np.asarray(lens))
    assert all(s == "bXYef" for s in out), out


def test_sharded_graph_propagation():
    from diamond_types_tpu.parallel.mesh import (make_mesh, pad_edges,
                                                 sharded_reach_fixed_point)
    # Fan-in DAG: 16 root runs all merged by one run.
    g = Graph()
    for i in range(16):
        g.push([], i * 10, i * 10 + 10)
    g.push([i * 10 + 9 for i in range(16)], 160, 170)
    packed = gk.pack_graph(g)
    n = packed["n"]
    src, plv, prun = pad_edges(packed, 8)
    reach0 = np.full((n,), -1, dtype=np.int32)
    reach0[16] = 169  # frontier at the merge tip

    mesh = make_mesh(8, axis="graph")
    reach = np.asarray(sharded_reach_fixed_point(
        mesh, packed["starts"], jnp.asarray(src), jnp.asarray(plv),
        jnp.asarray(prun), jnp.asarray(reach0)))
    # Every root run must be fully covered.
    assert all(reach[i] == i * 10 + 9 for i in range(16)), reach[:17]


def _fanin_graph(n_replicas: int, run_len: int = 8):
    """BASELINE config 5 shape: n_replicas concurrent root runs, one
    fan-in merge tip naming every replica's last LV as a parent."""
    g = Graph()
    for i in range(n_replicas):
        g.push([], i * run_len, (i + 1) * run_len)
    tip = n_replicas * run_len
    g.push([(i + 1) * run_len - 1 for i in range(n_replicas)], tip, tip + 4)
    return g, tip


def test_sharded_10k_replica_fanin():
    """The 10k-replica fan-in graph (BASELINE config 5) on the 8-device
    mesh: 10k edges shard evenly (edge-parallel CSR — the round-1 dense
    [n, max_parents] layout was O(n * 10k) memory and could not run)."""
    from diamond_types_tpu.parallel.mesh import (make_mesh, pad_edges,
                                                 sharded_reach_fixed_point)
    n_rep = 10_000
    g, tip = _fanin_graph(n_rep)
    packed = gk.pack_graph(g)
    assert packed["m"] == n_rep
    n = packed["n"]
    src, plv, prun = pad_edges(packed, 8)
    reach0 = np.full((n,), -1, dtype=np.int32)
    reach0[n - 1] = tip + 3

    mesh = make_mesh(8, axis="graph")
    reach = np.asarray(sharded_reach_fixed_point(
        mesh, packed["starts"], jnp.asarray(src), jnp.asarray(plv),
        jnp.asarray(prun), jnp.asarray(reach0)))
    assert (reach[:n_rep] == np.arange(1, n_rep + 1) * 8 - 1).all()

    # single-chip kernel agrees
    reach1 = np.asarray(gk.reach_fixed_point(
        packed, jnp.asarray(reach0)))
    assert (reach1 == reach).all()


def test_pallas_replay_matches_xla_path():
    """Pallas step kernel (interpret mode on CPU) vs the XLA replay path."""
    from diamond_types_tpu.tpu.pallas_kernels import replay_batch_pallas
    txns = [[(0, 0, "hello world")], [(5, 6, "")], [(5, 0, ", there")],
            [(0, 1, "H")], [(12, 0, "!")]]
    pos, dl, il, chars = encode_trace_ops(txns, max_ins=16)
    b = 4
    args = (jnp.asarray(np.tile(pos, (b, 1))), jnp.asarray(np.tile(dl, (b, 1))),
            jnp.asarray(np.tile(il, (b, 1))),
            jnp.asarray(np.tile(chars, (b, 1, 1))))
    ref_docs, ref_lens = replay_batch(*args, cap=64)
    docs, lens = replay_batch_pallas(*args, cap=64, interpret=True)
    assert np.array_equal(np.asarray(docs), np.asarray(ref_docs))
    assert np.array_equal(np.asarray(lens), np.asarray(ref_lens))


def test_replay_long_deletes_split_to_bound():
    """Deletes longer than max_ins exercise encode_trace_ops' split loop
    and the shift == -max_ins extreme of the static-roll select."""
    from diamond_types_tpu.text.trace import TestData, replay_direct
    txns = [[(0, 0, "hello there world")], [(5, 9, "")], [(0, 0, ">>")],
            [(2, 7, "")], [(0, 0, "ab")]]
    data = TestData("", "", txns)
    expected = replay_direct(data)

    for max_ins in (2, 4):
        pos, dl, il, chars = encode_trace_ops(txns, max_ins=max_ins)
        assert dl.max() <= max_ins and il.max() <= max_ins
        docs, lens = replay_batch(
            jnp.asarray(pos[None]), jnp.asarray(dl[None]),
            jnp.asarray(il[None]), jnp.asarray(chars[None]), cap=32)
        out = docs_to_strings(np.asarray(docs), np.asarray(lens))
        assert out[0] == expected, max_ins


def test_replay_out_of_contract_ops_poison_length():
    """Ops violating the dlen/ilen <= max_ins contract must not silently
    produce wrong text: the length comes back -1."""
    pos = np.zeros((1, 2), np.int32)
    il = np.asarray([[4, 0]], np.int32)
    dl = np.asarray([[0, 9]], np.int32)   # out of contract (max_ins = 4)
    chars = np.zeros((1, 2, 4), np.int32)
    chars[0, 0] = [104, 105, 33, 33]
    _docs, lens = replay_batch(jnp.asarray(pos), jnp.asarray(dl),
                               jnp.asarray(il), jnp.asarray(chars), cap=16)
    assert int(np.asarray(lens)[0]) == -1


def test_materialize_pallas_parity():
    """Pallas run-expansion (interpret mode) vs materialize_jax on random
    run tables and on a real corpus's device-doc tables."""
    import jax.numpy as jnp
    import numpy as np
    import random
    from diamond_types_tpu.tpu.linearize import materialize_jax
    from diamond_types_tpu.tpu.pallas_kernels import materialize_pallas

    rng = random.Random(77)
    for trial in range(12):
        n = rng.randint(1, 50)
        vis = np.array([rng.choice([0, 0, 1, 2, 5]) for _ in range(n)],
                       dtype=np.int32)
        arena = np.arange(1000, dtype=np.int32) + 100
        off = np.array([rng.randrange(900) for _ in range(n)],
                       dtype=np.int32)
        perm = np.random.RandomState(trial).permutation(n).astype(np.int32)
        cap = int(max(8, 1 << int(vis.sum()).bit_length()))
        t1, n1 = materialize_jax(jnp.asarray(perm), jnp.asarray(vis),
                                 jnp.asarray(off), jnp.asarray(arena),
                                 cap=cap)
        t2, n2 = materialize_pallas(jnp.asarray(perm), jnp.asarray(vis),
                                    jnp.asarray(off), jnp.asarray(arena),
                                    cap=cap, interpret=True)
        assert int(n1) == int(n2)
        assert np.array_equal(np.asarray(t1)[:int(n1)],
                              np.asarray(t2)[:int(n2)]), f"trial {trial}"


def test_materialize_pallas_corpus():
    """Byte parity through the full merge-kernel path with the Pallas
    materialize stage swapped in (friendsforever corpus)."""
    import numpy as np
    import jax.numpy as jnp
    from conftest import reference_path
    from diamond_types_tpu.encoding.decode import load_oplog
    from diamond_types_tpu.tpu.merge_kernel import prepare_doc
    from diamond_types_tpu.tpu.linearize import fugue_linearize_jax
    from diamond_types_tpu.tpu.pallas_kernels import materialize_pallas

    with open(reference_path("benchmark_data", "friendsforever.dt"),
              "rb") as f:
        ol = load_oplog(f.read())
    doc = prepare_doc(ol)
    n = doc.parent.shape[0]
    perm = fugue_linearize_jax(
        jnp.asarray(np.where(doc.parent == n, n, doc.parent)),
        jnp.asarray(doc.side.astype(np.int32)),
        jnp.asarray(doc.key_pos), jnp.asarray(doc.key_agent),
        jnp.asarray(doc.key_seq))
    cap = 1 << int(doc.total_len).bit_length()
    text, total = materialize_pallas(
        perm, jnp.asarray(doc.vis_len), jnp.asarray(doc.char_off),
        jnp.asarray(doc.chars), cap=cap, interpret=True)
    got = np.asarray(text)[:int(total)].astype(np.int32).tobytes() \
        .decode("utf-32-le")
    assert got == ol.checkout_tip().snapshot()


def test_pallas_kernels_lower_for_tpu():
    """Offline Mosaic lowering of every Pallas kernel (no TPU needed:
    .lower(lowering_platforms=('tpu',)) runs the full Mosaic kernel
    lowering pass on any backend).

    Regression for the 2026-07-31 on-chip failures: interpret-mode tests
    passed kernels the Mosaic backend cannot compile (first mismatched
    gather shapes, then dynamic_gather spanning multiple vregs — the
    backend limit that forced the gather-free redesign). The real
    tpu_merge_git_makefile_pallas bench died at compile time three
    rounds in a row while CI stayed green; this test makes the lowering
    contract a host-side assertion. (The backend's vreg-level layout
    checks run server-side only, so this cannot catch everything — the
    kernels are designed against the documented legal-op set instead:
    scalar-controlled rolls, dynamic-offset block copies, no gathers.)"""
    import unittest.mock as mock

    import jax
    import jax.numpy as jnp
    from diamond_types_tpu.tpu import pallas_kernels as pk
    from diamond_types_tpu.tpu.merge_kernel import _checkout_kernel

    perm = jnp.arange(200, dtype=jnp.int32)
    vis = jnp.ones(200, dtype=jnp.int32)
    aoff = jnp.arange(200, dtype=jnp.int32)
    arena = jnp.zeros(70000, dtype=jnp.int32)

    def mat(perm, vis, aoff, arena):
        return pk.materialize_pallas(perm, vis, aoff, arena, cap=300,
                                     interpret=False)

    # materialize_pallas consults jax.default_backend() to pick the
    # interpret fallback; pretend to be on TPU so the real kernel lowers.
    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        jax.jit(mat).trace(perm, vis, aoff, arena).lower(
            lowering_platforms=("tpu",))

    pos = jnp.zeros((8,), jnp.int32)
    dl = jnp.zeros((8,), jnp.int32)
    il = jnp.ones((8,), jnp.int32)
    ch = jnp.zeros((8, 16), jnp.int32)
    doc = jnp.zeros((8, 256), jnp.int32)
    dlen = jnp.zeros((8,), jnp.int32)
    jax.jit(lambda *a: pk.apply_op_block(*a, interpret=False)).trace(
        pos, dl, il, ch, doc, dlen).lower(lowering_platforms=("tpu",))

    # The production DT_TPU_PALLAS=1 entry point: the batch-unrolled
    # checkout (fugue linearize composed with the pallas materialize) —
    # the exact function bench_device_merge(pallas=True) compiles.
    B, n = 3, 64
    cols = (jnp.full((B, n), n, jnp.int32),          # parent (roots)
            jnp.zeros((B, n), jnp.int8),             # side
            jnp.zeros((B, n), jnp.int32),            # key_pos
            jnp.zeros((B, n), jnp.int32),            # key_agent
            jnp.arange(n, dtype=jnp.int32)[None].repeat(B, 0),  # key_seq
            jnp.ones((B, n), jnp.int32),             # vis_len
            jnp.arange(n, dtype=jnp.int32)[None].repeat(B, 0),  # char_off
            jnp.full((B, n), 97, jnp.int32))         # chars

    import functools

    def run_all(*cols):
        single = functools.partial(_checkout_kernel, cap=128, pallas=True)
        outs = [single(*(c[i] for c in cols)) for i in range(B)]
        return (jnp.stack([t for t, _ in outs]),
                jnp.stack([x for _, x in outs]))

    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        jax.jit(run_all).trace(*cols).lower(lowering_platforms=("tpu",))
