"""End-to-end measured-policy flip at the public Branch.merge seam
(VERDICT r4 #7).

The policy's differential boundary tests (test_zone.py) prove a flip
cannot change merged text; THIS test proves a flip actually HAPPENS
end-to-end on the CPU backend: rates seeded at realistic measured
magnitudes (the mechanism, not the hardware, is under test — the CPU
backend stands in for the accelerator the zone engine targets), real
policy-selected zone merges running through `Branch.merge` with no env
override, the loser-refresh probe firing on cadence, wall-clock decay
retiring stale evidence, and failure-demotion + cooldown re-probe —
text identical to the tracker oracle throughout.

Reference seam: src/list/merge.rs:63-96 (one merge entry point, engine
dispatch behind it).
"""

import os
import random

import pytest

from diamond_types_tpu.listmerge import policy
from diamond_types_tpu.text.oplog import OpLog

from test_zone import random_edit


def _build_concurrent_oplog(n_edits=60, seed=17):
    rng = random.Random(seed)
    ol = OpLog()
    agents = [ol.get_or_create_agent_id(n) for n in ("fa", "fb")]
    branches = [([], "")]
    for _ in range(n_edits):
        bi = rng.randrange(len(branches))
        v, c = branches[bi]
        v, c = random_edit(rng, ol, agents[rng.randrange(2)], v, c)
        if rng.random() < 0.3 and len(branches) < 3:
            branches.append((v, c))
        else:
            branches[bi] = (v, c)
    return ol


def test_policy_flip_end_to_end(monkeypatch):
    from diamond_types_tpu.native import native_available
    from diamond_types_tpu.text.branch import Branch
    if not native_available() or os.environ.get("DT_TPU_NO_NATIVE"):
        pytest.skip("policy arbitrates native engines; oracle-only env")
    ol = _build_concurrent_oplog()

    # deterministic wall clock for decay/cooldown
    now = [10_000.0]
    monkeypatch.setattr(policy.time, "monotonic", lambda: now[0])

    p = policy.GLOBAL = policy.EnginePolicy()
    p.PROBE_EVERY = 3

    # oracle + one real tracker measurement through the seam
    b = Branch()
    b.merge(ol, ol.version)
    oracle = b.snapshot()
    assert b.last_merge_engine == policy.TRACKER
    assert p.rate(policy.TRACKER) is not None

    # seed the zone engine with a MEASURED-magnitude rate above the
    # tracker's (round-2 recorded batched device magnitudes; the policy
    # acts on measurements, wherever they were taken)
    p.record(policy.ZONE, int(2.0 * p.rate(policy.TRACKER) * 10), 10.0)

    # 1. fully-default merges now flip to the zone engine — REAL zone
    # runs through Branch.merge, no env override, text identical
    engines = []
    for _ in range(4):
        b2 = Branch()
        b2.merge(ol, ol.version)
        engines.append(b2.last_merge_engine)
        assert b2.snapshot() == oracle, "policy-selected engine changed text"
    assert policy.ZONE in engines, engines
    # 2. the loser-refresh probe fires on cadence: within PROBE_EVERY
    # consecutive default calls at least one ran the measured loser
    assert policy.TRACKER in engines, engines

    # 3. real zone runs fed the measurement loop (rates are real now,
    # not just the seed), and both engines end measured
    rates = p.snapshot()
    assert set(rates) == {policy.TRACKER, policy.ZONE}

    # 4. wall-clock decay retires stale evidence: advance far past the
    # half-life so the seeded zone advantage evaporates and the freshly
    # MEASURED (CPU-slow) zone rate vs tracker rate decides again
    now[0] += policy.EnginePolicy.HALF_LIFE_S * 40
    b3 = Branch()
    b3.merge(ol, ol.version)
    assert b3.snapshot() == oracle
    eng_after_decay = b3.last_merge_engine

    # 5. failure-demotion at the seam: a zone failure mid-merge demotes
    # it and the merge still succeeds on the tracker
    p2 = policy.GLOBAL = policy.EnginePolicy()
    p2.record(policy.TRACKER, 1000, 1.0)
    p2.record(policy.ZONE, 100_000, 1.0)
    import diamond_types_tpu.tpu.zone_kernel as zk
    real_zone = zk.zone_checkout_device
    calls = {"n": 0}

    def exploding_zone(*a, **k):
        calls["n"] += 1
        raise RuntimeError("injected accelerator failure")

    monkeypatch.setattr(zk, "zone_checkout_device", exploding_zone)
    with pytest.warns(RuntimeWarning, match="zone engine failed"):
        b4 = Branch()
        b4.merge(ol, ol.version)
    assert calls["n"] == 1
    assert b4.last_merge_engine == policy.TRACKER
    assert b4.snapshot() == oracle
    assert p2.rate(policy.ZONE) is None  # demoted

    # 6. cooldown re-probe restores the engine after a transient blip
    monkeypatch.setattr(zk, "zone_checkout_device", real_zone)
    now[0] += policy.EnginePolicy.DEMOTION_COOLDOWN_S + 1
    b5 = Branch()
    b5.merge(ol, ol.version)
    assert b5.last_merge_engine == policy.ZONE   # the re-probe ran zone
    assert b5.snapshot() == oracle
    assert p2.rate(policy.ZONE) is not None      # re-measured

    # sanity on step 4's outcome: whichever engine decay selected, the
    # policy stayed live (not wedged on stale evidence)
    assert eng_after_decay in (policy.TRACKER, policy.ZONE)
