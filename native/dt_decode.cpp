// v1 "DMNDTYPS" oplog file decoder — the native L6 tier.
//
// Capability mirror of the reference decoder's fresh-load path
// (reference: src/list/encoding/decode_oplog.rs:447 ListOpLog::load_from;
// format spec BINARY.md:55-141): chunked format, LEB128 varints,
// per-column RLE, optional LZ4 block compression, CRC-32C. This unit
// handles loading a file into an EMPTY oplog (the common/benchmarked
// path: load_oplog, CLI, server startup); decode-and-add into a non-empty
// oplog (overlap dedup, foreign version maps) stays in the Python decoder
// (diamond_types_tpu/encoding/decode.py), which this parser mirrors
// column for column — the two are differentially tested against each
// other on every shipped corpus and fuzzed round-trips.
//
// Output is columnar: agent-name blobs, agent-assignment runs (LV order),
// RLE op rows merged with the same can_append rule as OpStore.push_op
// (so the Python rebuild produces byte-identical run tables), per-kind
// content blobs with per-row char lengths, and graph rows. The Python
// wrapper (encoding/decode.py) rebuilds the OpLog from these arrays.

#include <algorithm>
#include <array>
#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

typedef int64_t i64;
typedef uint8_t u8;

namespace dtdec {

// ---- errors --------------------------------------------------------------
// kind 1 = unsupported shape (caller should fall back to the Python
// decoder: e.g. patch files with a non-empty start version);
// kind 2 = hard parse/corruption error (caller raises ParseError).
struct Err {
  int kind;
  std::string msg;
};

#define FAIL(k, m) throw Err{k, m}

// ---- chunk ids (reference: src/list/encoding/mod.rs:29-60) --------------
enum {
  CH_COMPRESSED = 5,
  CH_FILEINFO = 1,
  CH_DOCID = 2,
  CH_AGENTNAMES = 3,
  CH_USERDATA = 4,
  CH_STARTBRANCH = 10,
  CH_VERSION = 12,
  CH_CONTENT = 13,
  CH_CONTENT_COMPRESSED = 14,
  CH_PATCHES = 20,
  CH_OP_VERSIONS = 21,
  CH_OP_TYPE_AND_POSITION = 22,
  CH_OP_PARENTS = 23,
  CH_PATCH_CONTENT = 24,
  CH_CONTENT_IS_KNOWN = 25,
  CH_CRC = 100,
};
static const int DATA_PLAIN_TEXT = 4;
static const int K_INS = 0, K_DEL = 1;

// ---- CRC-32C (Castagnoli, reflected 0x82F63B78) -------------------------
static uint32_t crc_table[256];
static bool crc_init_done = false;
static void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}
static uint32_t crc32c(const u8* d, i64 n) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  for (i64 i = 0; i < n; i++) crc = (crc >> 8) ^ crc_table[(crc ^ d[i]) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// ---- LZ4 block decompress -----------------------------------------------
static std::vector<u8> lz4_block(const u8* src, i64 n, i64 out_len) {
  std::vector<u8> out;
  out.reserve(out_len);
  i64 i = 0;
  while (i < n) {
    u8 token = src[i++];
    i64 lit = token >> 4;
    if (lit == 15) {
      while (true) {
        if (i >= n) FAIL(2, "lz4 truncated");
        u8 b = src[i++];
        lit += b;
        if (b != 255) break;
      }
    }
    if (lit) {
      if (i + lit > n) FAIL(2, "lz4 literal overrun");
      out.insert(out.end(), src + i, src + i + lit);
      i += lit;
    }
    if (i >= n) break;  // last sequence: literals only
    if (i + 2 > n) FAIL(2, "lz4 truncated offset");
    i64 offset = src[i] | (i64(src[i + 1]) << 8);
    i += 2;
    if (offset == 0) FAIL(2, "invalid LZ4 offset 0");
    i64 mlen = (token & 0xF) + 4;
    if ((token & 0xF) == 15) {
      while (true) {
        if (i >= n) FAIL(2, "lz4 truncated mlen");
        u8 b = src[i++];
        mlen += b;
        if (b != 255) break;
      }
    }
    i64 start = (i64)out.size() - offset;
    if (start < 0) FAIL(2, "LZ4 offset out of range");
    for (i64 k = 0; k < mlen; k++) out.push_back(out[start + k]);
  }
  if ((i64)out.size() != out_len) FAIL(2, "LZ4 length mismatch");
  return out;
}

// ---- buffer / varints ----------------------------------------------------
struct DBuf {
  const u8* d = nullptr;
  i64 pos = 0, end = 0;

  bool empty() const { return pos >= end; }

  i64 next_usize() {
    if (pos >= end) FAIL(2, "unexpected EOF");
    uint64_t result = 0;
    int shift = 0;
    while (true) {
      if (pos >= end) FAIL(2, "varint overruns chunk");
      u8 b = d[pos++];
      result |= (uint64_t)(b & 0x7F) << shift;
      if (b < 0x80) break;
      shift += 7;
      if (shift > 63) FAIL(2, "varint too long");
    }
    return (i64)result;
  }

  i64 next_zigzag() {
    i64 v = next_usize();
    return (v >> 1) * ((v & 1) ? -1 : 1);
  }

  const u8* next_n(i64 n) {
    if (pos + n > end) FAIL(2, "unexpected EOF");
    const u8* p = d + pos;
    pos += n;
    return p;
  }

  std::string next_str() {
    i64 n = next_usize();
    const u8* p = next_n(n);
    return std::string((const char*)p, (size_t)n);
  }

  DBuf next_chunk(i64* ctype) {
    *ctype = next_usize();
    i64 clen = next_usize();
    if (pos + clen > end) FAIL(2, "chunk overruns buffer");
    DBuf c{d, pos, pos + clen};
    pos += clen;
    return c;
  }

  i64 peek_type() {
    if (empty()) return -1;
    i64 p0 = pos;
    uint64_t result = 0;
    int shift = 0;
    while (true) {
      if (p0 >= end) return -1;
      u8 b = d[p0++];
      result |= (uint64_t)(b & 0x7F) << shift;
      if (b < 0x80) break;
      shift += 7;
      if (shift > 63) return -1;  // same bound as next_usize (no UB shift)
    }
    return (i64)result;
  }

  bool chunk_if_eq(i64 want, DBuf* out) {
    if (peek_type() != want) return false;
    i64 t;
    *out = next_chunk(&t);
    return true;
  }

  DBuf expect_chunk(i64 want) {
    i64 t;
    DBuf c = next_chunk(&t);
    if (t != want) FAIL(2, "expected chunk " + std::to_string(want) +
                              ", got " + std::to_string(t));
    return c;
  }
};

static void strip_bit(i64* v, bool* bit) {
  *bit = (*v & 1) != 0;
  *v >>= 1;
}

// ---- output rows ---------------------------------------------------------
struct OpRow {
  i64 lv, start, end;
  u8 kind, fwd, known;
  i64 char_len;  // chars consumed from the kind's content blob (0 if !known)
};
struct AgentRunRow {
  i64 agent, seq0, len;  // agent = file agent index, LV order
};
struct GraphRow {
  i64 start, end;
  std::vector<i64> parents;
};

struct Decoded {
  bool has_doc_id = false;
  std::string doc_id;
  std::vector<std::string> agent_names;
  std::vector<AgentRunRow> agent_runs;
  std::vector<OpRow> ops;
  std::string ins_blob, del_blob;
  std::vector<GraphRow> graph;
  Err err{0, ""};
};

// ---- column iterators ----------------------------------------------------
// Op type/position rows (mirrors decode.py _PatchesIter).
struct PatchesIter {
  DBuf buf;
  i64 cursor = 0;
  bool has_pushed = false;
  i64 p_kind, p_start, p_end;
  u8 p_fwd;

  bool next(i64* kind, i64* start, i64* end, u8* fwd) {
    if (has_pushed) {
      has_pushed = false;
      *kind = p_kind;
      *start = p_start;
      *end = p_end;
      *fwd = p_fwd;
      return true;
    }
    if (buf.empty()) return false;
    i64 n = buf.next_usize();
    bool has_length, diff_not_zero, is_del;
    strip_bit(&n, &has_length);
    strip_bit(&n, &diff_not_zero);
    strip_bit(&n, &is_del);
    i64 length, diff;
    bool f = true;
    if (has_length) {
      if (is_del) strip_bit(&n, &f);
      length = n;
      diff = diff_not_zero ? buf.next_zigzag() : 0;
    } else {
      length = 1;
      diff = (n >> 1) * ((n & 1) ? -1 : 1);
    }
    i64 raw_start = cursor + diff;
    i64 s, raw_end;
    if (!is_del && f) {
      s = raw_start;
      raw_end = raw_start + length;
    } else if (is_del && !f) {
      s = raw_start - length;
      raw_end = raw_start - length;
    } else {
      s = raw_start;
      raw_end = raw_start;
    }
    cursor = raw_end;
    *kind = is_del ? K_DEL : K_INS;
    *start = s;
    *end = s + length;
    *fwd = f ? 1 : 0;
    return true;
  }

  void push_back(i64 kind, i64 start, i64 end, u8 fwd) {
    has_pushed = true;
    p_kind = kind;
    p_start = start;
    p_end = end;
    p_fwd = fwd;
  }
};

// Per-kind content stream (mirrors decode.py _ContentIter). Emits
// (char_len, known) runs; the blob itself ships to Python whole.
struct ContentIter {
  DBuf runs;
  bool has_pushed = false;
  i64 p_len;
  u8 p_known;

  bool next(i64* len, u8* known) {
    if (has_pushed) {
      has_pushed = false;
      *len = p_len;
      *known = p_known;
      return true;
    }
    if (runs.empty()) return false;
    i64 n = runs.next_usize();
    bool k;
    strip_bit(&n, &k);
    *len = n;
    *known = k ? 1 : 0;
    return true;
  }

  void push_back(i64 len, u8 known) {
    has_pushed = true;
    p_len = len;
    p_known = known;
  }
};

// ---- op-row emitter with push_op's RLE merge rule -----------------------
// (mirrors text/op.py push_op + can_append_ops + append_ops)
static void emit_op(std::vector<OpRow>& out, i64 lv, i64 kind, i64 start,
                    i64 end, u8 fwd, u8 known, i64 char_len) {
  if (!out.empty()) {
    OpRow& a = out.back();
    i64 a_len = a.end - a.start, b_len = end - start;
    if (a.lv + a_len == lv && a.kind == kind && a.known == known) {
      bool can = false;
      bool af = a_len == 1 || a.fwd, bf = b_len == 1 || fwd;
      if (af && bf) {
        if (kind == K_INS && start == a.end) can = true;
        if (kind == K_DEL && start == a.start) can = true;
      }
      if (!can && kind == K_DEL && (a_len == 1 || !a.fwd) &&
          (b_len == 1 || !fwd) && end == a.start)
        can = true;
      if (can) {
        bool f = start >= a.start && (start != a.start || kind == K_DEL);
        a.fwd = f ? 1 : 0;
        if (kind == K_DEL && !f)
          a.start = start;
        else
          a.end += b_len;
        a.char_len += char_len;
        return;
      }
    }
  }
  out.push_back(OpRow{lv, start, end, (u8)kind, fwd, known, char_len});
}

// ---- the decoder ---------------------------------------------------------
static void decode(Decoded& out, const u8* data, i64 len) {
  if (len < 9 || std::memcmp(data, "DMNDTYPS", 8) != 0) FAIL(2, "bad magic");
  DBuf top{data, 8, len};
  if (top.next_usize() != 0) FAIL(2, "unsupported protocol version");

  // CRC scan first (decode.py checks before mutating).
  {
    DBuf scan{data, top.pos, len};
    while (!scan.empty()) {
      i64 mark = scan.pos;
      i64 t;
      DBuf c = scan.next_chunk(&t);
      if (t == CH_CRC) {
        const u8* p = c.next_n(4);
        uint32_t want = p[0] | (p[1] << 8) | ((uint32_t)p[2] << 16) |
                        ((uint32_t)p[3] << 24);
        if (crc32c(data, mark) != want) FAIL(2, "checksum failed");
        break;
      }
    }
  }

  std::vector<u8> decompressed;
  DBuf compressed{nullptr, 0, 0};
  bool has_compressed = false;
  {
    DBuf c5;
    if (top.chunk_if_eq(CH_COMPRESSED, &c5)) {
      i64 un_len = c5.next_usize();
      decompressed = lz4_block(c5.d + c5.pos, c5.end - c5.pos, un_len);
      compressed = DBuf{decompressed.data(), 0, (i64)decompressed.size()};
      has_compressed = true;
    }
  }

  auto content_str = [&](DBuf& parent) -> std::string {
    i64 t;
    DBuf r = parent.next_chunk(&t);
    if (t == CH_CONTENT) {
      if (r.next_usize() != DATA_PLAIN_TEXT) FAIL(2, "unknown content type");
      return std::string((const char*)r.d + r.pos, (size_t)(r.end - r.pos));
    } else if (t == CH_CONTENT_COMPRESSED) {
      if (r.next_usize() != DATA_PLAIN_TEXT) FAIL(2, "unknown content type");
      i64 n = r.next_usize();
      if (!has_compressed) FAIL(2, "compressed chunk missing");
      const u8* p = compressed.next_n(n);
      return std::string((const char*)p, (size_t)n);
    }
    FAIL(2, "expected content chunk");
    return std::string();  // unreachable
  };

  // --- FileInfo ---
  DBuf fileinfo = top.expect_chunk(CH_FILEINFO);
  {
    DBuf idc;
    if (fileinfo.chunk_if_eq(CH_DOCID, &idc)) {
      if (idc.next_usize() != DATA_PLAIN_TEXT) FAIL(2, "bad docid type");
      out.has_doc_id = true;
      out.doc_id.assign((const char*)idc.d + idc.pos,
                        (size_t)(idc.end - idc.pos));
    }
    DBuf names = fileinfo.expect_chunk(CH_AGENTNAMES);
    while (!names.empty()) out.agent_names.push_back(names.next_str());
    DBuf ud;
    fileinfo.chunk_if_eq(CH_USERDATA, &ud);
  }
  i64 n_agents = (i64)out.agent_names.size();

  // --- StartBranch (fresh load: must start at ROOT) ---
  {
    DBuf sb = top.expect_chunk(CH_STARTBRANCH);
    DBuf vc;
    if (sb.chunk_if_eq(CH_VERSION, &vc)) {
      while (true) {
        i64 n = vc.next_usize();
        bool has_more;
        strip_bit(&n, &has_more);
        vc.next_usize();  // seq
        if (n != 0)
          FAIL(1, "patch file (non-empty start version): python decoder "
                  "required");
        break;
      }
    }
    if (!sb.empty()) content_str(sb);  // start content (unused at ROOT)
  }

  // --- Patches ---
  DBuf patches = top.expect_chunk(CH_PATCHES);
  ContentIter ins_it, del_it;
  bool has_ins = false, has_del = false;
  while (patches.peek_type() == CH_PATCH_CONTENT) {
    i64 t;
    DBuf pc = patches.next_chunk(&t);
    i64 kind = pc.next_usize();
    if (kind != 0 && kind != 1) FAIL(2, "invalid content kind");
    std::string blob = content_str(pc);
    DBuf runs = pc.expect_chunk(CH_CONTENT_IS_KNOWN);
    if (kind == 0) {
      out.ins_blob = std::move(blob);
      ins_it.runs = runs;
      has_ins = true;
    } else {
      out.del_blob = std::move(blob);
      del_it.runs = runs;
      has_del = true;
    }
  }

  DBuf assignment = patches.expect_chunk(CH_OP_VERSIONS);
  DBuf type_pos = patches.expect_chunk(CH_OP_TYPE_AND_POSITION);
  DBuf history = patches.expect_chunk(CH_OP_PARENTS);

  PatchesIter ops_it;
  ops_it.buf = type_pos;

  i64 next_patch_time = 0;

  auto parse_next_patches = [&](i64 n) {
    while (n > 0) {
      i64 kind, start, end;
      u8 fwd;
      if (!ops_it.next(&kind, &start, &end, &fwd))
        FAIL(2, "patch column underrun");
      i64 max_len = std::min(n, end - start);
      ContentIter* cit = nullptr;
      if (kind == K_INS && has_ins) cit = &ins_it;
      if (kind == K_DEL && has_del) cit = &del_it;
      u8 known = 0;
      i64 char_here = 0;
      if (cit) {
        i64 clen;
        u8 ckn;
        if (!cit->next(&clen, &ckn)) FAIL(2, "content column underrun");
        max_len = std::min(max_len, clen);
        if (clen > max_len) cit->push_back(clen - max_len, ckn);
        known = ckn;
        char_here = ckn ? max_len : 0;
      }
      if (max_len <= 0) FAIL(2, "zero-length op row");
      n -= max_len;
      i64 s0 = start, e0 = end;
      if (max_len < end - start) {
        // split_op_loc(kind, start, end, fwd, max_len)
        i64 s1, e1;
        i64 length = end - start;
        if (kind == K_INS) {
          if (!fwd) FAIL(2, "reverse insert run in file");
          s0 = start;
          e0 = start + max_len;
          s1 = start + max_len;
          e1 = end;
        } else if (fwd) {
          s0 = start;
          e0 = start + max_len;
          s1 = start;
          e1 = start + (length - max_len);
        } else {  // del rev: tail first
          s0 = end - max_len;
          e0 = end;
          s1 = start;
          e1 = end - max_len;
        }
        ops_it.push_back(kind, s1, e1, fwd);
      }
      emit_op(out.ops, next_patch_time, kind, s0, e0, fwd, known, char_here);
      next_patch_time += max_len;
    }
  };

  // --- agent assignment (+ op columns, interleaved) ---
  std::vector<i64> seq_cursor(n_agents, 0);
  // per file-agent: (seq0, seq1, lv0) runs for foreign-parent lookup
  std::vector<std::vector<std::array<i64, 3>>> agent_lv(n_agents);
  i64 next_assignment_time = 0;
  while (!assignment.empty()) {
    i64 n = assignment.next_usize();
    bool has_jump;
    strip_bit(&n, &has_jump);
    i64 length = assignment.next_usize();
    i64 jump = has_jump ? assignment.next_zigzag() : 0;
    if (n == 0) FAIL(2, "op assigned to ROOT agent");
    if (n - 1 >= n_agents) FAIL(2, "invalid agent index");
    i64 agent = n - 1;
    i64 seq_start = seq_cursor[agent] + jump;
    seq_cursor[agent] = seq_start + length;
    out.agent_runs.push_back(AgentRunRow{agent, seq_start, length});
    agent_lv[agent].push_back({seq_start, seq_start + length,
                               next_assignment_time});
    parse_next_patches(length);
    next_assignment_time += length;
  }

  auto agent_seq_to_lv = [&](i64 agent, i64 seq) -> i64 {
    const auto& runs = agent_lv[agent];
    for (auto it = runs.rbegin(); it != runs.rend(); ++it)
      if ((*it)[0] <= seq && seq < (*it)[1]) return (*it)[2] + (seq - (*it)[0]);
    FAIL(2, "unknown foreign parent");
    return -1;  // unreachable
  };

  // --- history (parents) ---
  i64 next_file_time = 0;
  while (!history.empty()) {
    i64 length = history.next_usize();
    GraphRow row;
    row.start = next_file_time;
    row.end = next_file_time + length;
    while (true) {
      i64 n = history.next_usize();
      bool is_foreign, has_more;
      strip_bit(&n, &is_foreign);
      strip_bit(&n, &has_more);
      if (is_foreign) {
        if (n == 0) break;  // ROOT
        if (n - 1 >= n_agents) FAIL(2, "invalid parent agent");
        i64 seq = history.next_usize();
        row.parents.push_back(agent_seq_to_lv(n - 1, seq));
      } else {
        row.parents.push_back(next_file_time - n);
      }
      if (!has_more) break;
    }
    std::sort(row.parents.begin(), row.parents.end());
    next_file_time += length;
    out.graph.push_back(std::move(row));
  }

  if (next_patch_time != next_assignment_time ||
      next_patch_time != next_file_time)
    FAIL(2, "column length mismatch");

  // Content accounting: the sum of known-run char lengths must consume the
  // whole content blob exactly (the Python decoder raises "content
  // underrun"/"trailing content" for the same files; an aggregate check
  // rejects the identical input set and keeps every emitted content range
  // inside the arena).
  auto utf8_chars = [](const std::string& s) {
    i64 n = 0;
    for (unsigned char c : s)
      if ((c & 0xC0) != 0x80) n++;
    return n;
  };
  i64 want_ins = 0, want_del = 0;
  for (const auto& r : out.ops)
    (r.kind == K_INS ? want_ins : want_del) += r.char_len;
  if (has_ins && want_ins != utf8_chars(out.ins_blob))
    FAIL(2, "content underrun/trailing content (ins)");
  if (has_del && want_del != utf8_chars(out.del_blob))
    FAIL(2, "content underrun/trailing content (del)");
  if (!has_ins && !out.ins_blob.empty()) FAIL(2, "unexpected ins content");
  if (!has_del && !out.del_blob.empty()) FAIL(2, "unexpected del content");
}

}  // namespace dtdec

// ---- C ABI ---------------------------------------------------------------
extern "C" {

void* dt_decode_new(const u8* data, i64 len) {
  auto* d = new dtdec::Decoded();
  try {
    dtdec::decode(*d, data, len);
  } catch (const dtdec::Err& e) {
    d->err = e;
  } catch (const std::exception& e) {
    d->err = dtdec::Err{2, e.what()};
  }
  return d;
}

void dt_decode_free(void* h) { delete (dtdec::Decoded*)h; }

// 0 = ok, 1 = fall back to python, 2 = parse error (raise)
i64 dt_dec_status(void* h) { return ((dtdec::Decoded*)h)->err.kind; }

i64 dt_dec_err(void* h, char* buf, i64 cap) {
  const std::string& m = ((dtdec::Decoded*)h)->err.msg;
  i64 n = std::min<i64>(cap, (i64)m.size());
  if (n > 0) std::memcpy(buf, m.data(), n);
  return (i64)m.size();
}

// counts: [n_agents, names_bytes, n_agent_runs, n_ops, n_graph,
//          parents_total, ins_blob_bytes, del_blob_bytes,
//          has_doc_id, doc_id_bytes]
void dt_dec_counts(void* h, i64* out) {
  auto* d = (dtdec::Decoded*)h;
  i64 names_bytes = 0, parents = 0;
  for (const auto& s : d->agent_names) names_bytes += (i64)s.size();
  for (const auto& g : d->graph) parents += (i64)g.parents.size();
  out[0] = (i64)d->agent_names.size();
  out[1] = names_bytes;
  out[2] = (i64)d->agent_runs.size();
  out[3] = (i64)d->ops.size();
  out[4] = (i64)d->graph.size();
  out[5] = parents;
  out[6] = (i64)d->ins_blob.size();
  out[7] = (i64)d->del_blob.size();
  out[8] = d->has_doc_id ? 1 : 0;
  out[9] = (i64)d->doc_id.size();
}

void dt_dec_strings(void* h, u8* names, i64* name_lens, u8* ins_blob,
                    u8* del_blob, u8* doc_id) {
  auto* d = (dtdec::Decoded*)h;
  i64 k = 0;
  for (size_t i = 0; i < d->agent_names.size(); i++) {
    const std::string& s = d->agent_names[i];
    std::memcpy(names + k, s.data(), s.size());
    name_lens[i] = (i64)s.size();
    k += (i64)s.size();
  }
  std::memcpy(ins_blob, d->ins_blob.data(), d->ins_blob.size());
  std::memcpy(del_blob, d->del_blob.data(), d->del_blob.size());
  if (d->has_doc_id) std::memcpy(doc_id, d->doc_id.data(), d->doc_id.size());
}

void dt_dec_agent_runs(void* h, i64* agent, i64* seq0, i64* n) {
  auto* d = (dtdec::Decoded*)h;
  for (size_t i = 0; i < d->agent_runs.size(); i++) {
    agent[i] = d->agent_runs[i].agent;
    seq0[i] = d->agent_runs[i].seq0;
    n[i] = d->agent_runs[i].len;
  }
}

void dt_dec_ops(void* h, i64* lv, u8* kind, i64* start, i64* end, u8* fwd,
                u8* known, i64* char_len) {
  auto* d = (dtdec::Decoded*)h;
  for (size_t i = 0; i < d->ops.size(); i++) {
    const auto& r = d->ops[i];
    lv[i] = r.lv;
    kind[i] = r.kind;
    start[i] = r.start;
    end[i] = r.end;
    fwd[i] = r.fwd;
    known[i] = r.known;
    char_len[i] = r.char_len;
  }
}

void dt_dec_graph(void* h, i64* starts, i64* ends, i64* par_off,
                  i64* par_flat) {
  auto* d = (dtdec::Decoded*)h;
  i64 k = 0;
  for (size_t i = 0; i < d->graph.size(); i++) {
    starts[i] = d->graph[i].start;
    ends[i] = d->graph[i].end;
    par_off[i] = k;
    for (i64 p : d->graph[i].parents) par_flat[k++] = p;
  }
  par_off[d->graph.size()] = k;
}

i64 dt_crc32c(const u8* data, i64 n, i64 seed) {
  // same table/reflection as dtdec::crc32c but with a caller seed so the
  // Python incremental API (crc32c(data, crc)) maps 1:1
  dtdec::crc_init();
  uint32_t crc = (uint32_t)seed ^ 0xFFFFFFFFu;
  for (i64 i = 0; i < n; i++)
    crc = (crc >> 8) ^ dtdec::crc_table[(crc ^ data[i]) & 0xFF];
  return (i64)(crc ^ 0xFFFFFFFFu);
}

// Greedy LZ4 block compression — a byte-identical mirror of the Python
// lz4_compress_block (encoding/lz4.py): last-occurrence table keyed by the
// EXACT 4-byte value (not a truncated hash), matches >= 4, offsets <=
// 0xFFFF, final 5 bytes (+12-byte end window) literal. Byte identity
// matters: encoder output must not depend on whether the native library
// is loaded.
i64 dt_lz4_compress(const u8* src, i64 n, u8* out, i64 cap) {
  std::vector<u8> o;
  o.reserve(n + n / 255 + 16);
  std::unordered_map<uint32_t, i64> table;
  i64 anchor = 0, i = 0;
  i64 limit = n - 12;

  auto emit = [&](i64 lit_start, i64 lit_end, i64 match_off, i64 match_len) {
    i64 lit_len = lit_end - lit_start;
    int token_lit = lit_len >= 15 ? 15 : (int)lit_len;
    int token_match = 0;
    if (match_len >= 0) {
      i64 ml = match_len - 4;
      token_match = ml >= 15 ? 15 : (int)ml;
    }
    o.push_back((u8)((token_lit << 4) | token_match));
    if (lit_len >= 15) {
      i64 rem = lit_len - 15;
      while (rem >= 255) {
        o.push_back(255);
        rem -= 255;
      }
      o.push_back((u8)rem);
    }
    o.insert(o.end(), src + lit_start, src + lit_end);
    if (match_len >= 0) {
      o.push_back((u8)(match_off & 0xFF));
      o.push_back((u8)(match_off >> 8));
      if (match_len - 4 >= 15) {
        i64 rem = match_len - 4 - 15;
        while (rem >= 255) {
          o.push_back(255);
          rem -= 255;
        }
        o.push_back((u8)rem);
      }
    }
  };

  while (i < limit) {
    uint32_t key;
    std::memcpy(&key, src + i, 4);
    auto it = table.find(key);
    i64 cand = it == table.end() ? -1 : it->second;
    table[key] = i;
    if (cand >= 0 && i - cand <= 0xFFFF) {
      i64 m = 4;
      i64 max_m = n - 5 - i;
      while (m < max_m && src[cand + m] == src[i + m]) m++;
      if (m >= 4) {
        emit(anchor, i, i - cand, m);
        i += m;
        anchor = i;
        continue;
      }
    }
    i++;
  }
  emit(anchor, n, 0, -1);
  if ((i64)o.size() > cap) return -(i64)o.size();  // caller re-sizes
  std::memcpy(out, o.data(), o.size());
  return (i64)o.size();
}

}  // extern "C"
