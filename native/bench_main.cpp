// Standalone profiling harness for dt_core: loads columnar dumps produced by
// tools/dump_columns.py and runs the transform repeatedly (for gprof).
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <vector>
#include <cstdint>
typedef int64_t i64;
typedef uint8_t u8;

extern "C" {
void* dt_ctx_new();
void dt_add_agent(void*, const char*);
void dt_load_graph(void*, i64, const i64*, const i64*, const i64*, const i64*, const i64*);
void dt_load_agent_runs(void*, i64, const i64*, const i64*, const i64*, const i64*);
void dt_load_ops(void*, i64, const i64*, const u8*, const u8*, const i64*, const i64*, const i64*);
i64 dt_transform(void*, const i64*, i64, const i64*, i64);
i64 dt_merge_into_doc(void*, const int32_t*, i64, const i64*, i64,
                      const i64*, i64);
void dt_load_ins_arena(void*, i64, const int32_t*);
void dt_prof_dump();
}

template <class T>
std::vector<T> read_vec(FILE* f) {
  i64 n;
  if (fread(&n, 8, 1, f) != 1) { fprintf(stderr, "bad file\n"); exit(1); }
  std::vector<T> v(n);
  if (n && fread(v.data(), sizeof(T), n, f) != (size_t)n) exit(1);
  return v;
}

int main(int argc, char** argv) {
  if (argc < 2) { fprintf(stderr, "usage: %s dump.bin [iters]\n", argv[0]); return 1; }
  int iters = argc > 2 ? atoi(argv[2]) : 10;
  FILE* f = fopen(argv[1], "rb");
  if (!f) { perror("open"); return 1; }
  // 'DTCOL' + version; must match tools/dump_columns.py DUMP_MAGIC
  const i64 DUMP_MAGIC = 0x4454434F4C02ll;
  i64 magic;
  if (fread(&magic, 8, 1, f) != 1 || magic != DUMP_MAGIC) {
    fprintf(stderr,
            "stale or foreign dump (magic %llx, want %llx): regenerate "
            "with python -m diamond_types_tpu.tools.dump_columns\n",
            (unsigned long long)magic, (unsigned long long)DUMP_MAGIC);
    return 1;
  }
  i64 n_agents;
  fread(&n_agents, 8, 1, f);
  void* ctx = dt_ctx_new();
  for (i64 i = 0; i < n_agents; i++) {
    i64 len; fread(&len, 8, 1, f);
    std::vector<char> name(len + 1, 0);
    fread(name.data(), 1, len, f);
    dt_add_agent(ctx, name.data());
  }
  auto starts = read_vec<i64>(f);
  auto ends = read_vec<i64>(f);
  auto shadows = read_vec<i64>(f);
  auto indptr = read_vec<i64>(f);
  auto flat = read_vec<i64>(f);
  dt_load_graph(ctx, starts.size(), starts.data(), ends.data(), shadows.data(),
                indptr.data(), flat.data());
  auto lv0 = read_vec<i64>(f);
  auto lv1 = read_vec<i64>(f);
  auto ag = read_vec<i64>(f);
  auto sq = read_vec<i64>(f);
  dt_load_agent_runs(ctx, lv0.size(), lv0.data(), lv1.data(), ag.data(), sq.data());
  auto olv = read_vec<i64>(f);
  auto okind = read_vec<u8>(f);
  auto ofwd = read_vec<u8>(f);
  auto ost = read_vec<i64>(f);
  auto oen = read_vec<i64>(f);
  auto ocp = read_vec<i64>(f);
  auto arena = read_vec<int32_t>(f);
  dt_load_ops(ctx, olv.size(), olv.data(), okind.data(), ofwd.data(),
              ost.data(), oen.data(), ocp.data());
  dt_load_ins_arena(ctx, arena.size(), arena.data());
  auto ver = read_vec<i64>(f);
  fclose(f);
  i64 total = 0;
  double best = 1e18;
  // BENCH_DOC=1: time the full merge (transform + doc assembly) the
  // Python checkout path pays, not just the transform
  bool full_doc = getenv("BENCH_DOC") != nullptr;
  for (int it = 0; it < iters; it++) {
    auto t0 = std::chrono::steady_clock::now();
    if (full_doc)
      total += dt_merge_into_doc(ctx, nullptr, 0, nullptr, 0, ver.data(),
                                 ver.size());
    else
      total += dt_transform(ctx, nullptr, 0, ver.data(), ver.size());
    double dt = std::chrono::duration<double>(
        std::chrono::steady_clock::now() - t0).count();
    if (dt < best) best = dt;
  }
  dt_prof_dump();
  printf("best transform: %.2f ms\n", best * 1e3);
  printf("transform out rows total: %lld\n", (long long)total);
  return 0;
}
