// Native local-ingest session: the editor-typing hot path
// (OpLog.add_insert_at / add_delete_at) at native speed.
//
// The reference ingests local ops in native Rust (src/list/oplog.rs:
// 203-296 push_insert/push_delete over RleVec columns); this repo's
// per-op Python path tops out ~300k ops/s on the automerge-paper trace
// (BENCH_r04) because every op pays Python-object + method-call costs.
// This module keeps a SESSION of linear local edits (one agent, typing
// at the tip — the only shape local edits have) in C++ columnar runs,
// RLE-merged with the EXACT rules of text/op.py can_append_ops/
// append_ops, and drains them into the Python oplog in one bulk append
// (graph + agent assignment collapse to a single linear chain, which is
// what the Python path's per-op RLE would have produced anyway). The
// drained oplog is bit-identical to one built through the per-op Python
// path — tests/test_native_ingest.py proves semantic + encode parity.
//
// A CPython extension (not ctypes) because the per-call overhead is the
// whole point: METH_FASTCALL keeps one ins() call ~100ns.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

namespace {

typedef int64_t i64;

const int INS = 0, DEL = 1;

struct Run {
  i64 lv;
  int kind;
  i64 start, end;
  bool fwd;
  i64 cp0, cp1;  // arena char span, cp0 < 0 => no content
};

struct Session {
  std::vector<Run> runs;
  std::vector<uint32_t> ins_arena;
  std::vector<uint32_t> del_arena;
  i64 count = 0;  // LVs appended so far
  // Seed: a copy of the oplog's current LAST run, participating as the
  // merge target until the first non-mergeable op. Without it the
  // session's first merge decisions would be made against a fresh run
  // instead of the true predecessor, and the drained RLE structure
  // could diverge from what the per-op path builds (e.g. a backspace
  // continuing an existing reverse run, then a delete-key op at the
  // same position: per-op sees a reverse multi-run and starts a new
  // run; an unseeded session would merge them as a delete-key chain).
  bool has_seed = false;
  bool seed_dirty = false;        // any op merged into the seed
  Run seed{0, 0, 0, 0, true, -1, -1};
  i64 seed_content_appended = 0;  // arena chars merged into the seed
};

// mirror of text/op.py can_append_ops (reference: op_metrics.rs:235-256);
// b is always a fresh fwd run here (push_op pushes fwd=True), so the
// b-side guards reduce to: rule 1's (b_len==1 or b.fwd) is always true,
// rule 2's (b_len==1 or !b.fwd) is true only for single-item b
inline bool can_append(const Run& a, int kind, i64 b_start, i64 b_end) {
  i64 a_len = a.end - a.start;
  if (a_len == 1 || a.fwd) {
    if (kind == INS && b_start == a.end) return true;
    if (kind == DEL && b_start == a.start) return true;
  }
  if (kind == DEL && (a_len == 1 || !a.fwd) && b_end - b_start == 1) {
    if (b_end == a.start) return true;
  }
  return false;
}

// mirror of text/op.py append_ops (reference: op_metrics.rs:258-271)
inline void do_append(Run& a, int kind, i64 b_start, i64 b_end, i64 b_cp1) {
  bool fwd = b_start >= a.start && (b_start != a.start || kind == DEL);
  a.fwd = fwd;
  if (kind == DEL && !fwd)
    a.start = b_start;
  else
    a.end += b_end - b_start;
  if (a.cp0 >= 0 && b_cp1 >= 0) a.cp1 = b_cp1;
}

void push(Session* s, int kind, i64 start, i64 end, i64 cp0, i64 cp1) {
  Run* prev = nullptr;
  if (!s->runs.empty())
    prev = &s->runs.back();
  else if (s->has_seed)
    prev = &s->seed;
  if (prev && prev->kind == kind && (prev->cp0 >= 0) == (cp0 >= 0) &&
      can_append(*prev, kind, start, end)) {
    do_append(*prev, kind, start, end, cp1);
    if (prev == &s->seed) {
      s->seed_dirty = true;
      if (cp0 >= 0) s->seed_content_appended = cp1;
    }
    s->count += end - start;
    return;
  }
  s->runs.push_back({s->count, kind, start, end, true, cp0, cp1});
  s->count += end - start;
}

// append a PyUnicode's code points to an arena; returns (cp0, cp1)
bool arena_append(std::vector<uint32_t>& arena, PyObject* text, i64& cp0,
                  i64& cp1) {
  Py_ssize_t n = PyUnicode_GET_LENGTH(text);
  cp0 = (i64)arena.size();
  cp1 = cp0 + n;
  int kind = PyUnicode_KIND(text);
  const void* data = PyUnicode_DATA(text);
  size_t base = arena.size();
  arena.resize(base + (size_t)n);
  switch (kind) {
    case PyUnicode_1BYTE_KIND: {
      const Py_UCS1* p = (const Py_UCS1*)data;
      for (Py_ssize_t i = 0; i < n; i++) arena[base + i] = p[i];
      break;
    }
    case PyUnicode_2BYTE_KIND: {
      const Py_UCS2* p = (const Py_UCS2*)data;
      for (Py_ssize_t i = 0; i < n; i++) arena[base + i] = p[i];
      break;
    }
    default: {
      const Py_UCS4* p = (const Py_UCS4*)data;
      for (Py_ssize_t i = 0; i < n; i++) arena[base + i] = p[i];
      break;
    }
  }
  return true;
}

void sess_capsule_destroy(PyObject* cap) {
  Session* s = (Session*)PyCapsule_GetPointer(cap, "dt_ingest.session");
  delete s;
}

Session* get_sess(PyObject* cap) {
  return (Session*)PyCapsule_GetPointer(cap, "dt_ingest.session");
}

// new() or new(seed_kind, seed_start, seed_end, seed_fwd, seed_has_content)
PyObject* py_new(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 0 && nargs != 5) {
    PyErr_SetString(PyExc_TypeError,
                    "new([kind, start, end, fwd, has_content])");
    return nullptr;
  }
  Session* s = new Session();
  if (nargs == 5) {
    s->has_seed = true;
    s->seed.kind = (int)PyLong_AsLong(args[0]);
    s->seed.start = PyLong_AsLongLong(args[1]);
    s->seed.end = PyLong_AsLongLong(args[2]);
    s->seed.fwd = PyObject_IsTrue(args[3]);
    s->seed.cp0 = PyObject_IsTrue(args[4]) ? 0 : -1;
    if (PyErr_Occurred()) { delete s; return nullptr; }
  }
  return PyCapsule_New(s, "dt_ingest.session", sess_capsule_destroy);
}

// ins(sess, pos, text) -> total LV count after the op
PyObject* py_ins(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "ins(sess, pos, text)");
    return nullptr;
  }
  Session* s = get_sess(args[0]);
  if (!s) return nullptr;
  i64 pos = PyLong_AsLongLong(args[1]);
  if (pos < 0 && PyErr_Occurred()) return nullptr;
  PyObject* text = args[2];
  if (!PyUnicode_Check(text)) {
    PyErr_SetString(PyExc_TypeError, "text must be str");
    return nullptr;
  }
  Py_ssize_t n = PyUnicode_GET_LENGTH(text);
  if (n <= 0) {
    PyErr_SetString(PyExc_ValueError, "empty insert");
    return nullptr;
  }
  i64 cp0, cp1;
  arena_append(s->ins_arena, text, cp0, cp1);
  push(s, INS, pos, pos + n, cp0, cp1);
  return PyLong_FromLongLong(s->count);
}

// del_(sess, start, end[, content]) -> total LV count after the op
PyObject* py_del(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 3 && nargs != 4) {
    PyErr_SetString(PyExc_TypeError, "del_(sess, start, end[, content])");
    return nullptr;
  }
  Session* s = get_sess(args[0]);
  if (!s) return nullptr;
  i64 start = PyLong_AsLongLong(args[1]);
  i64 end = PyLong_AsLongLong(args[2]);
  if (PyErr_Occurred()) return nullptr;
  if (end <= start) {
    PyErr_SetString(PyExc_ValueError, "empty delete");
    return nullptr;
  }
  i64 cp0 = -1, cp1 = -1;
  if (nargs == 4 && args[3] != Py_None) {
    PyObject* content = args[3];
    if (!PyUnicode_Check(content)) {
      PyErr_SetString(PyExc_TypeError, "content must be str or None");
      return nullptr;
    }
    if (PyUnicode_GET_LENGTH(content) != end - start) {
      PyErr_SetString(PyExc_ValueError, "content length != delete length");
      return nullptr;
    }
    arena_append(s->del_arena, content, cp0, cp1);
  }
  push(s, DEL, start, end, cp0, cp1);
  return PyLong_FromLongLong(s->count);
}

PyObject* arena_to_str(const std::vector<uint32_t>& arena) {
  // explicit little-endian byteorder: with NULL the decoder sniffs (and
  // STRIPS) a leading U+FEFF as a BOM, silently shortening the arena;
  // surrogatepass so lone surrogates round-trip exactly like the pure-
  // Python path's str arenas (the server rejects them at the edge, but
  // the session must not be stricter than the path it mirrors)
  int byteorder = -1;
  return PyUnicode_DecodeUTF32((const char*)arena.data(),
                               (Py_ssize_t)arena.size() * 4,
                               "surrogatepass", &byteorder);
}

// drain(sess) -> (runs, ins_arena, del_arena, count, seed_info);
// resets the session. runs: list of (lv, kind, start, end, fwd, cp0,
// cp1) with cp0=-1 for content-less runs; lv/cp session-relative
// (base 0). seed_info: None when the seeded predecessor run was not
// extended, else (start, end, fwd, content_appended) — the seed run's
// final loc values and how many chars of the session's seed-kind arena
// were merged into it.
PyObject* py_drain(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 1) {
    PyErr_SetString(PyExc_TypeError, "drain(sess)");
    return nullptr;
  }
  Session* s = get_sess(args[0]);
  if (!s) return nullptr;
  PyObject* runs = PyList_New((Py_ssize_t)s->runs.size());
  if (!runs) return nullptr;
  for (size_t i = 0; i < s->runs.size(); i++) {
    const Run& r = s->runs[i];
    PyObject* t = Py_BuildValue("(LiLLOLL)", (long long)r.lv, r.kind,
                                (long long)r.start, (long long)r.end,
                                r.fwd ? Py_True : Py_False, (long long)r.cp0,
                                (long long)r.cp1);
    if (!t) { Py_DECREF(runs); return nullptr; }
    PyList_SET_ITEM(runs, (Py_ssize_t)i, t);
  }
  PyObject* ins_a = arena_to_str(s->ins_arena);
  PyObject* del_a = arena_to_str(s->del_arena);
  if (!ins_a || !del_a) {
    Py_XDECREF(ins_a); Py_XDECREF(del_a); Py_DECREF(runs);
    return nullptr;
  }
  PyObject* seed_info;
  if (s->seed_dirty) {
    seed_info = Py_BuildValue("(LLOL)", (long long)s->seed.start,
                              (long long)s->seed.end,
                              s->seed.fwd ? Py_True : Py_False,
                              (long long)s->seed_content_appended);
  } else {
    seed_info = Py_None;
    Py_INCREF(Py_None);
  }
  if (!seed_info) {
    Py_DECREF(ins_a); Py_DECREF(del_a); Py_DECREF(runs);
    return nullptr;
  }
  PyObject* out = Py_BuildValue("(NNNLN)", runs, ins_a, del_a,
                                (long long)s->count, seed_info);
  s->runs.clear();
  s->ins_arena.clear();
  s->del_arena.clear();
  s->count = 0;
  s->has_seed = false;
  s->seed_dirty = false;
  s->seed_content_appended = 0;
  return out;
}

PyObject* py_count(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
  if (nargs != 1) {
    PyErr_SetString(PyExc_TypeError, "count(sess)");
    return nullptr;
  }
  Session* s = get_sess(args[0]);
  if (!s) return nullptr;
  return PyLong_FromLongLong(s->count);
}

PyMethodDef methods[] = {
    {"new", (PyCFunction)py_new, METH_FASTCALL, "new() -> session"},
    {"ins", (PyCFunction)py_ins, METH_FASTCALL,
     "ins(sess, pos, text) -> count"},
    {"del_", (PyCFunction)py_del, METH_FASTCALL,
     "del_(sess, start, end[, content]) -> count"},
    {"drain", (PyCFunction)py_drain, METH_FASTCALL,
     "drain(sess) -> (runs, ins_arena, del_arena, count)"},
    {"count", (PyCFunction)py_count, METH_FASTCALL, "count(sess) -> int"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_dtingest",
                      "native local-ingest session", -1, methods,
                      nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__dtingest(void) { return PyModule_Create(&module); }
