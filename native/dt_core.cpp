// dt_core — native host core for diamond_types_tpu.
//
// Implements the merge-critical host path in C++ (the reference implements
// this tier in Rust; see SURVEY.md §2 native-component note):
//   * columnar causal graph + DAG queries (diff / find_conflicting)
//     (reference: src/causalgraph/graph/tools.rs)
//   * frontier movement (reference: src/frontier.rs)
//   * spanning-tree conflict walker (reference: src/listmerge/txn_trace.rs)
//   * treap-based merge tracker with dual current/upstream aggregates and
//     YjsMod integrate (reference: src/listmerge/merge.rs, yjsspan.rs,
//     advance_retreat.rs — same design as the Python tracker in
//     diamond_types_tpu/listmerge/tracker.py)
//   * the transformed-op pipeline incl. fast-forward mode
//     (reference: src/listmerge/merge.rs:585-941)
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
// Content (text) stays on the Python side; this library deals purely in
// LV spans and positions.

#include <algorithm>
#include <array>
#include <cassert>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <queue>
#include <string>
#include <vector>

typedef int64_t i64;
typedef uint8_t u8;

static const i64 ROOT = -1;
static const i64 UNDERWATER = 1ll << 62;

// ---------------------------------------------------------------- utilities

#ifdef DT_PROF
static long g_diff_calls = 0, g_diff_iters = 0;
long g_walk_steps = 0, g_walk_zero = 0, g_diff_iters2 = 0;
long g_orr_iters = 0;
#endif

// Always-on structured event counters around the merge kernel (SURVEY §5:
// the reference sketches these in its hot loops, merge.rs:311-314 /
// advance_retreat.rs:73-76; here they ship enabled — plain increments cost
// nothing next to the work they count). Exported via dt_get_counters; the
// name order is mirrored by native/core.py EVENT_COUNTER_NAMES.
struct EventCounters {
  unsigned long long integrate_calls = 0, integrate_scan_iters = 0,
      apply_ins_runs = 0, apply_del_runs = 0, advance_calls = 0,
      retreat_calls = 0, walk_steps = 0, diff_calls = 0;
};
static EventCounters g_events;

struct Span { i64 start, end; };

static inline bool span_empty(const Span& s) { return s.end <= s.start; }

static void push_reversed_rle(std::vector<Span>& out, Span s) {
  if (!out.empty() && s.end == out.back().start) out.back().start = s.start;
  else out.push_back(s);
}

// ---------------------------------------------------------------- graph

struct Graph {
  std::vector<i64> starts, ends, shadows;
  // parents in CSR layout (flat + indptr) for cache-friendly iteration
  std::vector<i64> pindptr, pflat;
  // dense LV -> entry index (LVs are 0..ends.back())
  std::vector<int32_t> idx_of;
  // diff-hot per-entry data packed in one line: start + inline parents
  struct DiffEnt { i64 start; int32_t np; i64 p[2]; };
  std::vector<DiffEnt> dent;

  inline size_t pn(size_t i) const { return pindptr[i + 1] - pindptr[i]; }
  inline const i64* pb(size_t i) const { return pflat.data() + pindptr[i]; }

  // The graph's version frontier (ascending): every entry-final LV that
  // no other entry references as a parent. Used by transform's trivial
  // checkout fast path (from=[] merging the full graph).
  std::vector<i64> heads;

  void build_idx() {
    idx_of.assign(starts.empty() ? 0 : (size_t)ends.back(), 0);
    for (size_t i = 0; i < starts.size(); i++)
      for (i64 v = starts[i]; v < ends[i]; v++) idx_of[v] = (int32_t)i;
    dent.resize(starts.size());
    for (size_t i = 0; i < starts.size(); i++) {
      dent[i].start = starts[i];
      size_t n = pn(i);
      dent[i].np = (int32_t)n;
      for (size_t k = 0; k < n && k < 2; k++) dent[i].p[k] = pb(i)[k];
    }
    heads.clear();
    std::vector<i64> ps(pflat);
    std::sort(ps.begin(), ps.end());
    for (i64 e : ends)
      if (!std::binary_search(ps.begin(), ps.end(), e - 1))
        heads.push_back(e - 1);
    std::sort(heads.begin(), heads.end());
  }

  inline size_t find_idx(i64 v) const { return idx_of[v]; }

  void parents_at(i64 v, std::vector<i64>& out) const {
    size_t i = find_idx(v);
    out.clear();
    if (v > starts[i]) out.push_back(v - 1);
    else out.assign(pb(i), pb(i) + pn(i));
  }

  bool entry_contains(size_t idx, i64 v) const {
    return starts[idx] <= v && v < ends[idx];
  }

  bool is_direct_descendant_coarse(i64 a, i64 b) const {
    if (a == b || b == ROOT) return true;
    return a > b && entry_contains(find_idx(a), b);
  }

  mutable std::vector<i64> fcv_heap;

  bool frontier_contains_version(const std::vector<i64>& f, i64 target) const {
    if (target == ROOT) return true;
    for (i64 o : f) if (o == target) return true;
    if (f.empty()) return false;
    for (i64 o : f) if (o > target && shadows[find_idx(o)] <= target) return true;
    std::vector<i64>& q = fcv_heap;
    q.clear();
    for (i64 o : f) if (o > target) q.push_back(o);
    std::make_heap(q.begin(), q.end());
    while (!q.empty()) {
      i64 order = q.front();
      std::pop_heap(q.begin(), q.end()); q.pop_back();
      size_t i = find_idx(order);
      if (shadows[i] <= target) return true;
      i64 start = starts[i];
      while (!q.empty() && q.front() >= start) {
        std::pop_heap(q.begin(), q.end()); q.pop_back();
      }
      for (size_t k = 0; k < pn(i); k++) {
        i64 p = pb(i)[k];
        if (p == target) return true;
        if (p > target) {
          q.push_back(p); std::push_heap(q.begin(), q.end());
        }
      }
    }
    return false;
  }

  // diff: returns (only_a, only_b) in DESCENDING order.
  enum Flag : u8 { OnlyA = 0, OnlyB = 1, Shared = 2 };

  void diff_rev(const std::vector<i64>& a, const std::vector<i64>& b,
                std::vector<Span>& only_a, std::vector<Span>& only_b) const {
    only_a.clear(); only_b.clear();
    if (a == b) return;
    if (a.size() == 1 && b.size() == 1) {
      if (is_direct_descendant_coarse(a[0], b[0])) {
        if (a[0] != b[0]) only_a.push_back({b[0] + 1, a[0] + 1});
        return;
      }
      if (is_direct_descendant_coarse(b[0], a[0])) {
        only_b.push_back({a[0] + 1, b[0] + 1});
        return;
      }
    }
    diff_slow(a, b, only_a, only_b);
  }

  mutable std::vector<std::pair<i64, u8>> diff_heap;

  void diff_slow(const std::vector<i64>& a, const std::vector<i64>& b,
                 std::vector<Span>& only_a, std::vector<Span>& only_b) const {
    // max-heap of (lv, flag)
    std::vector<std::pair<i64, u8>>& q = diff_heap;
    g_events.diff_calls++;
#ifdef DT_PROF
    g_diff_calls++;
#endif
    q.clear();
    for (i64 v : a) q.push_back({v, OnlyA});
    for (i64 v : b) q.push_back({v, OnlyB});
    std::make_heap(q.begin(), q.end());
    long num_shared = 0;

    auto mark = [&](i64 lo, i64 hi, u8 flag) {
      if (flag == Shared) return;
      push_reversed_rle(flag == OnlyA ? only_a : only_b, {lo, hi + 1});
    };
    auto pop = [&]() { std::pop_heap(q.begin(), q.end()); q.pop_back(); };
    auto push = [&](i64 v, u8 f) {
      q.push_back({v, f}); std::push_heap(q.begin(), q.end());
    };

    while (!q.empty()) {
#ifdef DT_PROF
      g_diff_iters++;
#endif
      auto [ord, flag] = q.front(); pop();
      if (flag == Shared) num_shared--;
      while (!q.empty() && q.front().first == ord) {
        u8 pf = q.front().second; pop();
        if (pf != flag) flag = Shared;
        if (pf == Shared) num_shared--;
      }
      size_t i = find_idx(ord);
      const DiffEnt& de = dent[i];
      i64 start = de.start;
      while (!q.empty() && q.front().first >= start) {
        i64 peek_ord = q.front().first; u8 pf = q.front().second;
        if (pf != flag) {
          mark(peek_ord + 1, ord, flag);
          ord = peek_ord;
          flag = Shared;
        }
        if (pf == Shared) num_shared--;
        pop();
      }
      mark(start, ord, flag);
      const i64* pp = de.np <= 2 ? de.p : pb(i);
      for (int32_t k = 0; k < de.np; k++) {
        push(pp[k], flag);
        if (flag == Shared) num_shared++;
      }
      if ((long)q.size() == num_shared) break;
    }
  }

  // find_conflicting; visits spans (descending); returns common ancestor.
  template <class V>
  std::vector<i64> find_conflicting(const std::vector<i64>& a,
                                    const std::vector<i64>& b, V visit) const {
    if (a == b) return a;
    if (a.size() == 1 && b.size() == 1) {
      if (is_direct_descendant_coarse(a[0], b[0])) {
        if (a[0] != b[0]) visit(Span{b[0] + 1, a[0] + 1}, (u8)OnlyA);
        return b[0] == ROOT ? std::vector<i64>{} : std::vector<i64>{b[0]};
      }
      if (is_direct_descendant_coarse(b[0], a[0])) {
        visit(Span{a[0] + 1, b[0] + 1}, (u8)OnlyB);
        return a[0] == ROOT ? std::vector<i64>{} : std::vector<i64>{a[0]};
      }
    }
    return find_conflicting_slow(a, b, visit);
  }

  struct TimePoint {
    i64 last;
    std::vector<i64> merged;  // sorted, excludes last
    bool operator==(const TimePoint& o) const {
      return last == o.last && merged == o.merged;
    }
    // max-heap: highest last first; among equal, FEWER merged first.
    bool operator<(const TimePoint& o) const {
      if (last != o.last) return last < o.last;
      if (merged.size() != o.merged.size()) return merged.size() > o.merged.size();
      return merged < o.merged;
    }
  };

  template <class V>
  std::vector<i64> find_conflicting_slow(const std::vector<i64>& a,
                                         const std::vector<i64>& b,
                                         V visit) const {
    auto tp = [](const std::vector<i64>& f) {
      TimePoint t;
      if (f.empty()) { t.last = ROOT; return t; }
      t.last = f.back();
      t.merged.assign(f.begin(), f.end() - 1);
      return t;
    };
    auto tpp = [this](size_t i) {
      TimePoint t;
      size_t n = pn(i);
      if (n == 0) { t.last = ROOT; return t; }
      t.last = pb(i)[n - 1];
      t.merged.assign(pb(i), pb(i) + n - 1);
      return t;
    };
    std::priority_queue<std::pair<TimePoint, u8>> q;
    q.push({tp(a), OnlyA});
    q.push({tp(b), OnlyB});

    while (true) {
      auto [time, flag] = q.top(); q.pop();
      i64 t = time.last;
      if (t == ROOT) return {};
      while (!q.empty() && q.top().first == time) {
        if (q.top().second != flag) flag = Shared;
        q.pop();
      }
      if (q.empty()) {
        std::vector<i64> fr = time.merged;
        fr.push_back(t);
        return fr;
      }
      for (i64 t2 : time.merged) q.push({TimePoint{t2, {}}, flag});
      size_t i = find_idx(t);
      Span rng{starts[i], t + 1};
      while (true) {
        if (!q.empty()) {
          const TimePoint& peek = q.top().first;
          if (peek.last != ROOT && peek.last >= starts[i]) {
            auto [time2, next_flag] = q.top(); q.pop();
            if (time2.last + 1 < rng.end) {
              i64 offset = time2.last + 1 - starts[i];
              Span rem{starts[i] + offset, rng.end};
              rng = {starts[i], starts[i] + offset};
              visit(rem, flag);
            }
            for (i64 t2 : time2.merged) q.push({TimePoint{t2, {}}, next_flag});
            if (next_flag != flag) flag = Shared;
          } else {
            visit(rng, flag);
            q.push({tpp(i), flag});
            break;
          }
        } else {
          return {rng.end - 1};
        }
      }
    }
  }

  // frontier ops (reference: src/frontier.rs)
  void advance_known_run(std::vector<i64>& f, const std::vector<i64>& ps,
                         Span span) const {
    i64 last = span.end - 1;
    if (ps.size() == 1 && f.size() == 1 && ps[0] == f[0]) { f[0] = last; return; }
    if (f == ps) { f.assign(1, last); return; }
    std::vector<i64> out;
    for (i64 o : f)
      if (std::find(ps.begin(), ps.end(), o) == ps.end()) out.push_back(o);
    out.insert(std::upper_bound(out.begin(), out.end(), last), last);
    f = out;
  }

  // parents scratch for advance/retreat: one malloc per merge instead of
  // one per call (transform advances the frontier once per walk step).
  // Contexts are driven single-threaded (the Python side serializes per
  // oplog), so a mutable scratch on a const method is safe here.
  mutable std::vector<i64> ps_scratch;

  void advance(std::vector<i64>& f, Span rng) const {
    i64 start = rng.start;
    size_t i = find_idx(start);
    std::vector<i64>& ps = ps_scratch;
    while (true) {
      i64 e_end = std::min(ends[i], rng.end);
      parents_at(start, ps);
      advance_known_run(f, ps, {start, e_end});
      if (e_end >= rng.end) break;
      start = e_end;
      i++;
    }
  }

  void retreat(std::vector<i64>& f, Span rng) const {
    if (span_empty(rng)) return;
    i64 start = rng.start, end = rng.end;
    size_t i = find_idx(end - 1);
    std::vector<i64>& ps = ps_scratch;
    while (true) {
      i64 last_order = end - 1;
      i64 t_start = starts[i];
      if (f.size() == 1) {
        if (start > t_start) { f[0] = start - 1; break; }
        f.assign(pb(i), pb(i) + pn(i));
      } else {
        f.erase(std::remove(f.begin(), f.end(), last_order), f.end());
        parents_at(std::max(start, t_start), ps);
        for (i64 p : ps) {
          if (!frontier_contains_version(f, p))
            f.insert(std::upper_bound(f.begin(), f.end(), p), p);
        }
      }
      if (start >= t_start) break;
      end = t_start;
      i--;
    }
  }
};

// ---------------------------------------------------------------- agents

struct AgentRun { i64 seq_start, seq_end, lv_start; };

struct Agents {
  std::vector<std::string> names;
  std::vector<std::vector<AgentRun>> client_runs;
  // global: (lv_start, lv_end, agent, seq_start), lv-sorted
  struct GRun { i64 lv0, lv1; i64 agent, seq0; };
  std::vector<GRun> global_runs;

  std::vector<int32_t> idx_of;  // dense LV -> global run index

  void build_idx() {
    i64 top = 0;
    for (const GRun& g : global_runs) top = std::max(top, g.lv1);
    idx_of.assign((size_t)top, 0);
    for (size_t i = 0; i < global_runs.size(); i++)
      for (i64 v = global_runs[i].lv0; v < global_runs[i].lv1; v++)
        idx_of[v] = (int32_t)i;
  }

  inline const GRun& find_global(i64 lv) const {
    if (lv < (i64)idx_of.size()) return global_runs[idx_of[lv]];
    size_t lo = 0, hi = global_runs.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (global_runs[mid].lv0 <= lv) lo = mid + 1; else hi = mid; }
    return global_runs[lo - 1];
  }

  void local_to_agent(i64 lv, i64& agent, i64& seq) const {
    const GRun& g = find_global(lv);
    agent = g.agent;
    seq = g.seq0 + (lv - g.lv0);
  }

  i64 span_len(i64 lv, i64 max_len) const {
    const GRun& g = find_global(lv);
    return std::min(g.lv1 - lv, max_len);
  }
};

// ---------------------------------------------------------------- op store

struct OpRun { i64 lv; u8 kind; u8 fwd; i64 start, end; i64 cp; };
static const u8 INS = 0, DEL = 1;

struct Ops {
  std::vector<OpRun> runs;
  std::vector<int32_t> idx_of;  // dense LV -> run index

  void build_idx() {
    i64 top = 0;
    for (const OpRun& r : runs) top = std::max(top, r.lv + (r.end - r.start));
    idx_of.assign((size_t)top, 0);
    for (size_t i = 0; i < runs.size(); i++) {
      i64 e = runs[i].lv + (runs[i].end - runs[i].start);
      for (i64 v = runs[i].lv; v < e; v++) idx_of[v] = (int32_t)i;
    }
  }

  inline size_t find_idx(i64 lv) const {
    if (lv < (i64)idx_of.size()) return idx_of[lv];
    size_t lo = 0, hi = runs.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (runs[mid].lv <= lv) lo = mid + 1; else hi = mid; }
    return lo - 1;
  }

  // sub-run covering item offsets [o0, o1) of run r
  static OpRun slice(const OpRun& r, i64 o0, i64 o1) {
    i64 n = r.end - r.start;
    if (o0 == 0 && o1 == n) return r;
    OpRun out = r;
    out.lv = r.lv + o0;
    if (r.cp >= 0) out.cp = r.cp + o0;
    i64 s, e;
    if (r.kind == INS) {
      s = r.start + o0; e = s + (o1 - o0);
    } else if (r.fwd) {
      s = r.start; e = s + (o1 - o0);
    } else {
      s = r.end - o1; e = r.end - o0;
    }
    out.start = s; out.end = e;
    return out;
  }
};

// ---------------------------------------------------------------- tracker
//
// Fat-leaf order-statistic B-tree of YjsSpan runs, the same design as the
// reference's content-tree (crates/content-tree/src/lib.rs:64, node sizes
// :33-41) with the dual current/upstream metric (src/listmerge/metrics.rs:
// 18-66). The LV -> leaf "space index" (reference: src/listmerge/markers.rs
// MarkerEntry / InsPtr) is a B+ tree of RLE runs keyed by LV, updated by a
// notify hook when entries move between leaves.

struct BLeaf;

// One YjsSpan run (reference: src/listmerge/yjsspan.rs:25-45).
struct BEntry {
  i64 ids;        // id (LV) of first item
  i64 len;
  i64 ol, orr;    // origin left / right
  int32_t state;  // 0 NIY, 1 inserted, >=2 deleted (state-1) times
  bool ever;
  inline i64 ide() const { return ids + len; }
  inline i64 cur() const { return state == 1 ? len : 0; }
  inline i64 up() const { return ever ? 0 : len; }
  inline i64 origin_left_at(i64 off) const {
    return off == 0 ? ol : ids + off - 1;
  }
};

static const int LEAF_CAP = 32;   // entries per leaf (16 was best for the
// FF-era workload; the round-5 zone-everything merge pushes whole
// histories through the tracker and re-measured best at 32 — nn -17%)
static const int NODE_CAP = 16;   // children per internal node

struct BNode;

struct BLeaf {
  int n = 0;
  BNode* parent = nullptr;
  int pslot = 0;
  BLeaf *next = nullptr, *prev = nullptr;
  BEntry e[LEAF_CAP];
};

struct BNode {
  int n = 0;
  bool leaf_children = true;
  BNode* parent = nullptr;
  int pslot = 0;
  void* ch[NODE_CAP];
  i64 raw[NODE_CAP], cur[NODE_CAP], up[NODE_CAP];
};

// ---- LV -> BLeaf* index: B+ tree of RLE runs keyed by LV ----

struct IRun { i64 key, len; BLeaf* ptr; };
static const int IL_CAP = 32;
static const int IN_CAP = 16;

struct INodeI;
struct ILeaf {
  int n = 0;
  INodeI* parent = nullptr;
  int pslot = 0;
  ILeaf *next = nullptr, *prev = nullptr;
  IRun r[IL_CAP];
};
struct INodeI {
  int n = 0;
  bool leaf_children = true;
  INodeI* parent = nullptr;
  int pslot = 0;
  i64 k0[IN_CAP];
  void* ch[IN_CAP];
};

struct SpaceIndex {
  std::deque<ILeaf> leaf_pool;
  std::deque<INodeI> node_pool;
  INodeI* root;

  SpaceIndex() {
    leaf_pool.emplace_back();
    node_pool.emplace_back();
    root = &node_pool.back();
    root->leaf_children = true;
    root->n = 1;
    root->k0[0] = INT64_MIN;
    root->ch[0] = &leaf_pool.back();
    leaf_pool.back().parent = root;
  }

  ILeaf* descend(i64 key) const {
    INodeI* nd = root;
    while (true) {
      int i = nd->n - 1;
      while (i > 0 && nd->k0[i] > key) i--;
      if (nd->leaf_children) {
        ILeaf* lf = (ILeaf*)nd->ch[i];
        // separators can be stale-low; the containing run may live in an
        // earlier leaf (see set_range erase semantics).
        while (lf->prev && (lf->n == 0 || key < lf->r[0].key)) lf = lf->prev;
        return lf;
      }
      nd = (INodeI*)nd->ch[i];
    }
  }

  BLeaf* query(i64 key) const {
    ILeaf* lf = descend(key);
    int lo = 0, hi = lf->n;
    while (lo < hi) { int mid = (lo + hi) / 2;
      if (lf->r[mid].key <= key) lo = mid + 1; else hi = mid; }
    assert(lo > 0 && key < lf->r[lo - 1].key + lf->r[lo - 1].len);
    return lf->r[lo - 1].ptr;
  }

  void split_inode(INodeI* nd) {
    while (nd->n == IN_CAP) {
      node_pool.emplace_back();
      INodeI* rn = &node_pool.back();
      int half = IN_CAP / 2;
      rn->leaf_children = nd->leaf_children;
      rn->n = IN_CAP - half;
      for (int i = 0; i < rn->n; i++) {
        rn->k0[i] = nd->k0[half + i];
        rn->ch[i] = nd->ch[half + i];
        if (rn->leaf_children) {
          ((ILeaf*)rn->ch[i])->parent = rn; ((ILeaf*)rn->ch[i])->pslot = i;
        } else {
          ((INodeI*)rn->ch[i])->parent = rn; ((INodeI*)rn->ch[i])->pslot = i;
        }
      }
      nd->n = half;
      INodeI* par = nd->parent;
      if (!par) {
        node_pool.emplace_back();
        INodeI* nr = &node_pool.back();
        nr->leaf_children = false;
        nr->n = 2;
        nr->k0[0] = nd->k0[0]; nr->ch[0] = nd;
        nr->k0[1] = rn->k0[0]; nr->ch[1] = rn;
        nd->parent = nr; nd->pslot = 0;
        rn->parent = nr; rn->pslot = 1;
        root = nr;
        return;
      }
      int at = nd->pslot + 1;
      for (int i = par->n; i > at; i--) {
        par->k0[i] = par->k0[i - 1]; par->ch[i] = par->ch[i - 1];
        if (par->leaf_children) ((ILeaf*)par->ch[i])->pslot = i;
        else ((INodeI*)par->ch[i])->pslot = i;
      }
      par->k0[at] = rn->k0[0];
      par->ch[at] = rn;
      rn->parent = par; rn->pslot = at;
      par->n++;
      nd = par;
    }
  }

  // Insert run at position `at` in leaf lf (splitting the leaf if full).
  void insert_run(ILeaf* lf, int at, IRun run) {
    if (lf->n == IL_CAP) {
      leaf_pool.emplace_back();
      ILeaf* rn = &leaf_pool.back();
      int half = IL_CAP / 2;
      rn->n = IL_CAP - half;
      std::memcpy(rn->r, lf->r + half, rn->n * sizeof(IRun));
      lf->n = half;
      rn->next = lf->next; if (rn->next) rn->next->prev = rn;
      rn->prev = lf; lf->next = rn;
      INodeI* par = lf->parent;
      if (par->n == IN_CAP) { split_inode(par); par = lf->parent; }
      int slot = lf->pslot + 1;
      for (int i = par->n; i > slot; i--) {
        par->k0[i] = par->k0[i - 1]; par->ch[i] = par->ch[i - 1];
        ((ILeaf*)par->ch[i])->pslot = i;
      }
      par->k0[slot] = rn->r[0].key;
      par->ch[slot] = rn;
      rn->parent = par; rn->pslot = slot;
      par->n++;
      if (at > half) { at -= half; lf = rn; }
    }
    for (int i = lf->n; i > at; i--) lf->r[i] = lf->r[i - 1];
    lf->r[at] = run;
    lf->n++;
  }

  // Location-returning insert (position of the inserted run).
  std::pair<ILeaf*, int> insert_run_ret(ILeaf* lf, int at, IRun run) {
    if (lf->n == IL_CAP) {
      // same split as insert_run, but track where `at` lands
      insert_run(lf, at, run);
      // find it again (rare path): run.key uniquely identifies it
      ILeaf* l2 = lf;
      while (l2) {
        for (int i = 0; i < l2->n; i++)
          if (l2->r[i].key == run.key) return {l2, i};
        l2 = l2->next;
      }
      assert(false);
      return {lf, at};
    }
    for (int i = lf->n; i > at; i--) lf->r[i] = lf->r[i - 1];
    lf->r[at] = run;
    lf->n++;
    return {lf, at};
  }

  // Remove all coverage of [key, end). Returns the location where a run
  // starting at `key` should be inserted to keep global key order.
  std::pair<ILeaf*, int> erase_range(i64 key, i64 end) {
    ILeaf* lf = descend(key);
    int lo = 0, hi = lf->n;
    while (lo < hi) { int mid = (lo + hi) / 2;
      if (lf->r[mid].key <= key) lo = mid + 1; else hi = mid; }
    int at = lo;  // first run with r.key > key
    if (at > 0) {
      IRun& pv = lf->r[at - 1];
      i64 pend = pv.key + pv.len;
      if (pend > key) {  // pv overlaps [key, ..)
        if (pv.key == key) {
          if (pend > end) {
            pv.key = end; pv.len = pend - end;
            return {lf, at - 1};
          }
          for (int i = at - 1; i < lf->n - 1; i++) lf->r[i] = lf->r[i + 1];
          lf->n--; at--;
        } else {
          pv.len = key - pv.key;
          if (pend > end) {
            // hole carved in the middle of pv: keep the tail
            return insert_run_ret(lf, at, IRun{end, pend - end, pv.ptr});
          }
        }
      }
    }
    // remove following runs fully covered; trim a partial overlap
    while (true) {
      if (at == lf->n) {
        ILeaf* nx = lf->next;
        if (!nx) return {lf, at};
        if (nx->n == 0) { lf = nx; at = 0; continue; }
        if (nx->r[0].key >= end) return {lf, at};
        lf = nx; at = 0;
        continue;
      }
      IRun& r = lf->r[at];
      if (r.key >= end) return {lf, at};
      i64 rend = r.key + r.len;
      if (rend <= end) {
        for (int i = at; i < lf->n - 1; i++) lf->r[i] = lf->r[i + 1];
        lf->n--;
      } else {
        r.len = rend - end;
        r.key = end;
        return {lf, at};
      }
    }
  }

  // Overwrite [key, key+len) to map to ptr.
  void set_range(i64 key, i64 len, BLeaf* ptr) {
    i64 end = key + len;
    auto [lf, at] = erase_range(key, end);
    // merge with left neighbor
    IRun* pv = nullptr;
    ILeaf* plf = nullptr;
    if (at > 0) { pv = &lf->r[at - 1]; plf = lf; }
    else if (lf->prev && lf->prev->n) {
      plf = lf->prev; pv = &plf->r[plf->n - 1];
    }
    if (pv && pv->key + pv->len == key && pv->ptr == ptr) {
      pv->len += len;
      // absorb right neighbor too if now contiguous
      if (at < lf->n && lf->r[at].key == end && lf->r[at].ptr == ptr &&
          plf == lf) {
        pv->len += lf->r[at].len;
        for (int i = at; i < lf->n - 1; i++) lf->r[i] = lf->r[i + 1];
        lf->n--;
      }
      return;
    }
    // merge with right neighbor
    if (at < lf->n && lf->r[at].key == end && lf->r[at].ptr == ptr) {
      lf->r[at].key = key; lf->r[at].len += len;
      return;
    }
    insert_run(lf, at, IRun{key, len, ptr});
  }
};

struct Cursor { BLeaf* leaf; int idx; i64 off; };  // leaf==nullptr => doc end

struct DelRow { i64 lv0, lv1, t0, t1; bool fwd; };

struct Tracker {
  std::deque<BLeaf> leaf_pool;
  std::deque<BNode> node_pool;
  BNode* root;
  BLeaf* first_leaf;
  // LV -> containing tree leaf, split by range: op LVs are dense in
  // [0, ops_top) -> O(1) table; underwater placeholder ids (>= 1<<62,
  // origin-right sentinels and pre-existing text hit by concurrent
  // deletes) -> small RLE B+ tree. Together they replace the reference's
  // marker tree InsPtr half (src/listmerge/markers.rs).
  std::vector<BLeaf*> leaf_of;
  SpaceIndex uw_index;
  // delete targets: op LVs are dense, so an O(1) run table replaces the
  // reference's marker-tree DelTarget entries (src/listmerge/markers.rs)
  std::vector<DelRow> del_list;
  std::vector<int32_t> del_run_of;  // op lv -> del_list index, -1 = none
  // Genuinely colliding concurrent inserts seen by integrate (reference:
  // merge_conflict_checks, listmerge/mod.rs:50-51 — counted whenever the
  // scan meets another item that is not simply our origin-right).
  i64 collisions = 0;

  // Forward-delete continuation memo: a long delete run is applied in
  // entry-bounded chunks with an unchanged current position (the text
  // shifts left under it, Ops::slice keeps .start fixed for fwd deletes).
  // After a partial chunk we stash the rolled-forward cursor + upstream
  // prefix so the continuation call skips the root descent. Invalidated by
  // any other tree mutation (inserts, toggles, reverse deletes).
  i64 del_cont_pos = -1;
  i64 del_cont_up = 0;
  Cursor del_cont_cursor{nullptr, 0, 0};

  // Dense tables cover only [base, ops_top) — the conflict zone's LV
  // range — so per-merge cost scales with the zone, not the full history.
  i64 base;

  explicit Tracker(i64 zone_base, i64 ops_top) : base(zone_base) {
    del_run_of.assign((size_t)(ops_top - base), -1);
    leaf_of.assign((size_t)(ops_top - base), nullptr);
    leaf_pool.emplace_back();
    node_pool.emplace_back();
    root = &node_pool.back();
    first_leaf = &leaf_pool.back();
    first_leaf->parent = root;
    first_leaf->n = 1;
    first_leaf->e[0] = BEntry{UNDERWATER, UNDERWATER - 1, ROOT, ROOT, 1, false};
    root->leaf_children = true;
    root->n = 1;
    root->ch[0] = first_leaf;
    root->raw[0] = UNDERWATER - 1;
    root->cur[0] = UNDERWATER - 1;
    root->up[0] = UNDERWATER - 1;
    uw_index.set_range(UNDERWATER, UNDERWATER - 1, first_leaf);
  }

  inline void set_leaf(i64 ids, i64 len, BLeaf* lf) {
    if (ids < UNDERWATER) {
      assert(ids >= base && ids + len - base <= (i64)leaf_of.size());
      std::fill(leaf_of.begin() + (ids - base),
                leaf_of.begin() + (ids + len - base), lf);
    } else {
      uw_index.set_range(ids, len, lf);
    }
  }

  // ---- aggregate maintenance ----

  static inline void bump(BLeaf* lf, i64 draw, i64 dcur, i64 dup) {
    BNode* nd = lf->parent;
    int slot = lf->pslot;
    while (nd) {
      nd->raw[slot] += draw; nd->cur[slot] += dcur; nd->up[slot] += dup;
      slot = nd->pslot;
      nd = nd->parent;
    }
  }

  static void leaf_totals(const BLeaf* lf, i64& raw, i64& cur, i64& up) {
    raw = cur = up = 0;
    for (int i = 0; i < lf->n; i++) {
      raw += lf->e[i].len; cur += lf->e[i].cur(); up += lf->e[i].up();
    }
  }

  // ---- structure mutation ----

  void split_internal(BNode* nd) {
    while (nd->n == NODE_CAP) {
      node_pool.emplace_back();
      BNode* rn = &node_pool.back();
      int half = NODE_CAP / 2;
      rn->leaf_children = nd->leaf_children;
      rn->n = NODE_CAP - half;
      for (int i = 0; i < rn->n; i++) {
        rn->ch[i] = nd->ch[half + i];
        rn->raw[i] = nd->raw[half + i];
        rn->cur[i] = nd->cur[half + i];
        rn->up[i] = nd->up[half + i];
        if (rn->leaf_children) {
          ((BLeaf*)rn->ch[i])->parent = rn; ((BLeaf*)rn->ch[i])->pslot = i;
        } else {
          ((BNode*)rn->ch[i])->parent = rn; ((BNode*)rn->ch[i])->pslot = i;
        }
      }
      nd->n = half;
      i64 raw = 0, cur = 0, up = 0;
      for (int i = 0; i < rn->n; i++) {
        raw += rn->raw[i]; cur += rn->cur[i]; up += rn->up[i];
      }
      BNode* par = nd->parent;
      if (!par) {
        node_pool.emplace_back();
        BNode* nr = &node_pool.back();
        nr->leaf_children = false;
        nr->n = 2;
        i64 lraw = 0, lcur = 0, lup = 0;
        for (int i = 0; i < nd->n; i++) {
          lraw += nd->raw[i]; lcur += nd->cur[i]; lup += nd->up[i];
        }
        nr->ch[0] = nd; nr->raw[0] = lraw; nr->cur[0] = lcur; nr->up[0] = lup;
        nr->ch[1] = rn; nr->raw[1] = raw; nr->cur[1] = cur; nr->up[1] = up;
        nd->parent = nr; nd->pslot = 0;
        rn->parent = nr; rn->pslot = 1;
        root = nr;
        return;
      }
      int at = nd->pslot + 1;
      for (int i = par->n; i > at; i--) {
        par->ch[i] = par->ch[i - 1];
        par->raw[i] = par->raw[i - 1];
        par->cur[i] = par->cur[i - 1];
        par->up[i] = par->up[i - 1];
        ((BNode*)par->ch[i])->pslot = i;
      }
      par->ch[at] = rn;
      par->raw[at] = raw; par->cur[at] = cur; par->up[at] = up;
      par->raw[nd->pslot] -= raw; par->cur[nd->pslot] -= cur;
      par->up[nd->pslot] -= up;
      rn->parent = par; rn->pslot = at;
      par->n++;
      nd = par;
    }
  }

  // Split a full leaf; moved entries are re-registered in the space index.
  // Returns the new right leaf.
  BLeaf* split_leaf(BLeaf* lf) {
    leaf_pool.emplace_back();
    BLeaf* rn = &leaf_pool.back();
    int half = LEAF_CAP / 2;
    rn->n = LEAF_CAP - half;
    std::memcpy(rn->e, lf->e + half, rn->n * sizeof(BEntry));
    lf->n = half;
    rn->next = lf->next; if (rn->next) rn->next->prev = rn;
    rn->prev = lf; lf->next = rn;
    i64 raw, cur, up;
    leaf_totals(rn, raw, cur, up);
    BNode* par = lf->parent;
    if (par->n == NODE_CAP) { split_internal(par); par = lf->parent; }
    int at = lf->pslot + 1;
    for (int i = par->n; i > at; i--) {
      par->ch[i] = par->ch[i - 1];
      par->raw[i] = par->raw[i - 1];
      par->cur[i] = par->cur[i - 1];
      par->up[i] = par->up[i - 1];
      ((BLeaf*)par->ch[i])->pslot = i;
    }
    par->ch[at] = rn;
    par->raw[at] = raw; par->cur[at] = cur; par->up[at] = up;
    par->raw[lf->pslot] -= raw; par->cur[lf->pslot] -= cur;
    par->up[lf->pslot] -= up;
    rn->parent = par; rn->pslot = at;
    par->n++;
    // notify: moved entries now live in rn
    for (int i = 0; i < rn->n; i++)
      set_leaf(rn->e[i].ids, rn->e[i].len, rn);
    return rn;
  }

  // Insert `ent` at position (lf, at); returns the entry's new location.
  std::pair<BLeaf*, int> insert_entry(BLeaf* lf, int at, const BEntry& ent) {
    if (lf->n == LEAF_CAP) {
      BLeaf* rn = split_leaf(lf);
      if (at > lf->n) { at -= lf->n; lf = rn; }
    }
    for (int i = lf->n; i > at; i--) lf->e[i] = lf->e[i - 1];
    lf->e[at] = ent;
    lf->n++;
    bump(lf, ent.len, ent.cur(), ent.up());
    return {lf, at};
  }

  // Split entry (lf, idx) at offset `off` (0 < off < len). Returns the
  // location of the LEFT half; the right half sits at (leaf, idx+1) of the
  // returned location (guaranteed same leaf).
  std::pair<BLeaf*, int> split_entry(BLeaf* lf, int idx, i64 off) {
    BLeaf* orig = lf;
    BEntry right{lf->e[idx].ids + off, lf->e[idx].len - off,
                 lf->e[idx].ids + off - 1, lf->e[idx].orr,
                 lf->e[idx].state, lf->e[idx].ever};
    lf->e[idx].len = off;
    bump(lf, -right.len, -right.cur(), -right.up());
    if (lf->n == LEAF_CAP) {
      BLeaf* rn = split_leaf(lf);
      if (idx >= lf->n) { idx -= lf->n; lf = rn; }
    }
    for (int i = lf->n; i > idx + 1; i--) lf->e[i] = lf->e[i - 1];
    lf->e[idx + 1] = right;
    lf->n++;
    bump(lf, right.len, right.cur(), right.up());
    if (lf != orig) set_leaf(right.ids, right.len, lf);
    return {lf, idx};
  }

  // ---- lookup ----

  mutable BLeaf* hint_leaf = nullptr;
  mutable int hint_idx = 0;

  // (leaf, idx) of the entry containing lv
  std::pair<BLeaf*, int> ins_lookup(i64 lv) const {
    // LV ranges are globally disjoint, so a containment hit on the hint is
    // always the right entry; leaves live in a pool, so probing is safe.
    BLeaf* h = hint_leaf;
    if (h) {
      int i = hint_idx;
      if (i < h->n && h->e[i].ids <= lv && lv < h->e[i].ide()) return {h, i};
      if (i + 1 < h->n && h->e[i + 1].ids <= lv && lv < h->e[i + 1].ide()) {
        hint_idx = i + 1;
        return {h, i + 1};
      }
    }
    BLeaf* lf;
    if (lv < UNDERWATER) {
      assert(lv >= base && lv - base < (i64)leaf_of.size());
      lf = leaf_of[lv - base];
    } else {
      lf = uw_index.query(lv);
    }
    for (int i = 0; i < lf->n; i++)
      if (lf->e[i].ids <= lv && lv < lf->e[i].ide()) {
        hint_leaf = lf; hint_idx = i;
        return {lf, i};
      }
#ifdef DT_DEBUG_LOOKUP
    fprintf(stderr, "ins_lookup MISS lv=%lld mapped=%p\n", (long long)lv, (void*)lf);
    for (const BLeaf* sl = first_leaf; sl; sl = sl->next)
      for (int i = 0; i < sl->n; i++)
        if (sl->e[i].ids <= lv && lv < sl->e[i].ide()) {
          fprintf(stderr, "  actual leaf=%p idx=%d ids=%lld len=%lld\n",
                  (void*)sl, i, (long long)sl->e[i].ids, (long long)sl->e[i].len);
          abort();
        }
    fprintf(stderr, "  lv not in ANY leaf\n");
    abort();
#endif
    assert(false && "ins_lookup: lv not in mapped leaf");
    return {nullptr, 0};
  }

  // Returns the cursor for current-position pos; *up_out (optional) gets
  // the upstream-length prefix BEFORE the returned entry.
  Cursor find_by_cur(i64 pos, i64* up_out = nullptr) const {
    BNode* nd = root;
    i64 up = 0;
    while (true) {
      int i = 0;
      while (pos >= nd->cur[i]) {
        pos -= nd->cur[i]; up += nd->up[i]; i++;
        assert(i < nd->n);
      }
      if (nd->leaf_children) {
        BLeaf* lf = (BLeaf*)nd->ch[i];
        for (int j = 0; j < lf->n; j++) {
          i64 c = lf->e[j].cur();
          if (pos < c) { if (up_out) *up_out = up; return {lf, j, pos}; }
          pos -= c;
          up += lf->e[j].up();
        }
        assert(false && "find_by_cur: pos out of range");
      }
      nd = (BNode*)nd->ch[i];
    }
  }

  i64 prefix(const Cursor& c, int which) const {
    // which: 0 raw, 1 cur, 2 up
    i64 acc = 0;
    const BLeaf* lf = c.leaf;
    for (int i = 0; i < c.idx; i++) {
      const BEntry& e = lf->e[i];
      acc += which == 0 ? e.len : which == 1 ? e.cur() : e.up();
    }
    const BNode* nd = lf->parent;
    int slot = lf->pslot;
    while (nd) {
      const i64* agg = which == 0 ? nd->raw : which == 1 ? nd->cur : nd->up;
      for (int i = 0; i < slot; i++) acc += agg[i];
      slot = nd->pslot;
      nd = nd->parent;
    }
    return acc;
  }

  i64 total(int which) const {
    const i64* agg = which == 0 ? root->raw : which == 1 ? root->cur
                                            : root->up;
    i64 acc = 0;
    for (int i = 0; i < root->n; i++) acc += agg[i];
    return acc;
  }

  i64 raw_pos(const Cursor& c) const {
    if (!c.leaf) return total(0);
    return prefix(c, 0) + c.off;
  }

  i64 upstream_pos(const Cursor& c) const {
    if (!c.leaf) return total(2);
    return prefix(c, 2) + (c.leaf->e[c.idx].ever ? 0 : c.off);
  }

  // normalize so off < entry len; {nullptr} at end of doc
  bool roll(Cursor& c) const {
    if (!c.leaf) return false;
    while (c.off >= c.leaf->e[c.idx].len) {
      c.off -= c.leaf->e[c.idx].len;
      c.idx++;
      while (c.idx >= c.leaf->n) {
        if (!c.leaf->next) { c.leaf = nullptr; c.idx = 0; c.off = 0; return false; }
        c.leaf = c.leaf->next;
        c.idx = 0;
      }
    }
    return true;
  }

  // step to the next entry (ignores off)
  static bool next_entry(Cursor& c) {
    c.idx++; c.off = 0;
    while (c.idx >= c.leaf->n) {
      if (!c.leaf->next) { c.leaf = nullptr; c.idx = 0; return false; }
      c.leaf = c.leaf->next;
      c.idx = 0;
    }
    return true;
  }

  Cursor cursor_before_item(i64 lv) const {
    if (lv == ROOT) return {nullptr, 0, 0};  // end sentinel
    auto [lf, i] = ins_lookup(lv);
    return {lf, i, lv - lf->e[i].ids};
  }

  Cursor cursor_after_item(i64 lv) const {
    if (lv == ROOT) {
      BLeaf* lf = first_leaf;
      Cursor c{lf, 0, 0};
      roll(c);
      return c;
    }
    auto [lf, i] = ins_lookup(lv);
    Cursor c{lf, i, lv - lf->e[i].ids + 1};
    roll(c);
    return c;
  }

  int cmp_cursors(const Cursor& a, const Cursor& b) const {
    if (a.leaf == b.leaf) {
      if (a.idx != b.idx) return a.idx < b.idx ? -1 : 1;
      return a.off < b.off ? -1 : a.off > b.off ? 1 : 0;
    }
    i64 pa = raw_pos(a), pb = raw_pos(b);
    return pa < pb ? -1 : pa > pb ? 1 : 0;
  }

  // Try to RLE-merge entry (lf, idx) into its doc-order predecessor
  // (reference: YjsSpan::can_append, yjsspan.rs:168-174).
  void try_merge_left(BLeaf* lf, int idx) {
    BEntry& en = lf->e[idx];
    if (en.ol != en.ids - 1) return;
    if (idx > 0) {
      BEntry& pv = lf->e[idx - 1];
      if (pv.ide() != en.ids || pv.orr != en.orr ||
          pv.state != en.state || pv.ever != en.ever) return;
      pv.len += en.len;
      for (int i = idx; i < lf->n - 1; i++) lf->e[i] = lf->e[i + 1];
      lf->n--;
      // aggregates unchanged (same leaf, same totals); index unchanged.
    } else {
      BLeaf* pl = lf->prev;
      if (!pl || pl->n == 0 || lf->n <= 1) return;  // keep leaves non-empty
      BEntry& pv = pl->e[pl->n - 1];
      if (pv.ide() != en.ids || pv.orr != en.orr ||
          pv.state != en.state || pv.ever != en.ever) return;
      i64 raw = en.len, cur = en.cur(), up = en.up();
      pv.len += en.len;
      set_leaf(en.ids, en.len, pl);
      for (int i = 0; i < lf->n - 1; i++) lf->e[i] = lf->e[i + 1];
      lf->n--;
      bump(pl, raw, cur, up);
      bump(lf, -raw, -cur, -up);
    }
  }

  // Insert a new item entry at cursor position (splitting as needed).
  // Returns nothing; caller already computed positions.
  void insert_at(const Cursor& c, const BEntry& ent) {
    BLeaf* lf; int at;
    if (!c.leaf) {
      // end of doc: append after last entry of rightmost leaf
      BNode* nd = root;
      while (!nd->leaf_children) nd = (BNode*)nd->ch[nd->n - 1];
      lf = (BLeaf*)nd->ch[nd->n - 1];
      at = lf->n;
    } else if (c.off == 0) {
      lf = c.leaf; at = c.idx;
    } else if (c.off == c.leaf->e[c.idx].len) {
      lf = c.leaf; at = c.idx + 1;
    } else {
      auto [l2, i2] = split_entry(c.leaf, c.idx, c.off);
      lf = l2; at = i2 + 1;  // insert before the right half
    }
    // RLE append fast path: extend the left neighbor when the new item is
    // its linear continuation.
    BEntry* pv = nullptr;
    BLeaf* pvleaf = nullptr;
    if (at > 0) { pv = &lf->e[at - 1]; pvleaf = lf; }
    else if (lf->prev && lf->prev->n) {
      pvleaf = lf->prev; pv = &pvleaf->e[pvleaf->n - 1];
    }
    if (pv && ent.ol == ent.ids - 1 && pv->ide() == ent.ids &&
        pv->orr == ent.orr && pv->state == ent.state && pv->ever == ent.ever) {
      pv->len += ent.len;
      bump(pvleaf, ent.len, ent.cur(), ent.up());
      set_leaf(ent.ids, ent.len, pvleaf);
      return;
    }
    auto [l3, i3] = insert_entry(lf, at, ent);
    set_leaf(ent.ids, ent.len, l3);
  }

  // `up` is the upstream-length prefix before cursor's entry; threaded
  // through the scan so the final position needs no tree climb.
  i64 integrate(const Agents& aa, i64 agent, const BEntry& item,
                Cursor cursor, i64 up) {
    g_events.integrate_calls++;
    // roll, accumulating crossed entries into the upstream prefix
    auto roll_up = [&](Cursor& c) -> bool {
      if (!c.leaf) return false;
      while (c.off >= c.leaf->e[c.idx].len) {
        c.off -= c.leaf->e[c.idx].len;
        up += c.leaf->e[c.idx].up();
        c.idx++;
        while (c.idx >= c.leaf->n) {
          if (!c.leaf->next) { c.leaf = nullptr; c.idx = 0; c.off = 0; return false; }
          c.leaf = c.leaf->next;
          c.idx = 0;
        }
      }
      return true;
    };
    bool at_end = !roll_up(cursor);
    Cursor left_cursor = cursor;
    Cursor scan_start = cursor;
    i64 scan_up = up;
    bool scanning = false;

    while (!at_end && cursor.leaf) {
      g_events.integrate_scan_iters++;
      if (!roll_up(cursor)) break;
      const BEntry& other = cursor.leaf->e[cursor.idx];
      i64 off = cursor.off;
      i64 other_lv = other.ids + off;
      if (other_lv == item.orr) break;
      collisions++;
      assert(other.state == 0);

      i64 other_left_lv = other.origin_left_at(off);
      Cursor olc = cursor_after_item(other_left_lv);
      int c = cmp_cursors(olc, left_cursor);
      if (c < 0) break;
      if (c == 0) {
        if (item.orr == other.orr) {
          i64 oa, oseq;
          aa.local_to_agent(other_lv, oa, oseq);
          const std::string& my_name = aa.names[agent];
          const std::string& other_name = aa.names[oa];
          bool ins_here;
          if (my_name < other_name) ins_here = true;
          else if (my_name == other_name) {
            i64 ma, mseq;
            aa.local_to_agent(item.ids, ma, mseq);
            ins_here = mseq < oseq;
          } else ins_here = false;
          if (ins_here) break;
          scanning = false;
        } else {
          Cursor mr = cursor_before_item(item.orr);
          Cursor orc = cursor_before_item(other.orr);
          if (cmp_cursors(orc, mr) < 0) {
            if (!scanning) { scanning = true; scan_start = cursor; scan_up = up; }
          } else scanning = false;
        }
      }
      up += cursor.leaf->e[cursor.idx].up();
      if (!next_entry(cursor)) {
        cursor = {nullptr, 0, 0};
        break;
      }
    }
    if (scanning) { cursor = scan_start; up = scan_up; }
    Cursor at = cursor.leaf ? cursor : Cursor{nullptr, 0, 0};
    i64 pos;
    if (!at.leaf) pos = up;
    else pos = up + (at.leaf->e[at.idx].ever ? 0 : at.off);
    insert_at(at, item);
    return pos;
  }

  // returns (consumed, xf_pos) — xf_pos = -1 => delete already happened
  std::pair<i64, i64> apply(const Agents& aa, i64 agent, const OpRun& op,
                            i64 max_len) {
    i64 length = std::min(max_len, op.end - op.start);
    if (op.kind == INS) {
      del_cont_pos = -1;
      assert(op.fwd && "reverse insert runs unsupported");
      i64 origin_left;
      Cursor cursor;
      i64 up_prefix = 0;
      if (op.start == 0) {
        origin_left = ROOT;
        cursor = {first_leaf, 0, 0};
      } else {
        Cursor c = find_by_cur(op.start - 1, &up_prefix);
        origin_left = c.leaf->e[c.idx].ids + c.off;
        cursor = {c.leaf, c.idx, c.off + 1};
      }
      // origin_right: next non-NIY item at-or-after cursor
      Cursor c2 = cursor;
      i64 origin_right = ROOT;
      if (roll(c2)) {
        while (true) {
#ifdef DT_PROF
          extern long g_orr_iters;
          g_orr_iters++;
#endif
          const BEntry& e = c2.leaf->e[c2.idx];
          if (e.state == 0) {
            if (!next_entry(c2)) { origin_right = ROOT; break; }
          } else { origin_right = e.ids + c2.off; break; }
        }
      }
      BEntry item{op.lv, length, origin_left, origin_right, 1, false};
      i64 pos = integrate(aa, agent, item, cursor, up_prefix);
      return {length, pos};
    } else {
      bool fwd = op.fwd;
      Cursor cursor;
      i64 take_req;
      i64 up_prefix = 0;
      if (fwd) {
        if (op.start == del_cont_pos) {
          cursor = del_cont_cursor;
          up_prefix = del_cont_up;
        } else {
          cursor = find_by_cur(op.start, &up_prefix);
        }
        take_req = length;
      } else {
        i64 last_pos = op.end - 1;
        Cursor c = find_by_cur(last_pos, &up_prefix);
        i64 entry_start_pos = last_pos - c.off;
        i64 edit_start = std::max(entry_start_pos, op.end - length);
        take_req = op.end - edit_start;
        cursor = {c.leaf, c.idx, c.off - (take_req - 1)};
      }
      BLeaf* lf = cursor.leaf;
      int idx = cursor.idx;
      i64 off = cursor.off;
      assert(lf->e[idx].state == 1);
      bool ever_deleted = lf->e[idx].ever;
      i64 del_start_xf =
          up_prefix + (lf->e[idx].ever ? 0 : off);
      i64 take = std::min(take_req, lf->e[idx].len - off);
      if (off > 0) {
        auto [l2, i2] = split_entry(lf, idx, off);
        lf = l2; idx = i2 + 1;  // right half
      }
      if (take < lf->e[idx].len) {
        auto [l2, i2] = split_entry(lf, idx, take);
        lf = l2; idx = i2;  // left half
      }
      BEntry& en = lf->e[idx];
      i64 t0 = en.ids, t1 = en.ide();
      i64 dcur = en.state == 1 ? -(t1 - t0) : 0;
      i64 dup = en.ever ? 0 : -(t1 - t0);
      en.state += 1;
      en.ever = true;
      bump(lf, 0, dcur, dup);

      assert(op.lv >= base &&
             op.lv + take - base <= (i64)del_run_of.size());
      int32_t ri = (int32_t)del_list.size();
      del_list.push_back(DelRow{op.lv, op.lv + take, t0, t1, fwd});
      for (i64 v = op.lv; v < op.lv + take; v++) del_run_of[v - base] = ri;
      del_cont_pos = -1;
      if (fwd && take < take_req) {
        // roll to the next current entry for the continuation chunk,
        // folding crossed entries into the upstream prefix (left split
        // half contributes its pre-delete up(), the target now 0)
        i64 up2 = up_prefix + (ever_deleted ? 0 : off);
        Cursor c{lf, idx, 0};
        while (next_entry(c)) {
          const BEntry& ne = c.leaf->e[c.idx];
          if (ne.state == 1) break;
          up2 += ne.up();
        }
        if (c.leaf) {
          del_cont_cursor = c;
          del_cont_up = up2;
          del_cont_pos = op.start;
        }
      }
      return {take, ever_deleted ? -1 : del_start_xf};
    }
  }

  // ---- advance / retreat ----

  struct QueryRes { u8 kind; i64 t0, t1; bool fwd; i64 offset, total; };

  QueryRes index_query(i64 lv) const {
    assert(lv >= base && lv - base < (i64)del_run_of.size());
    if (del_run_of[lv - base] >= 0) {
      const DelRow& r = del_list[del_run_of[lv - base]];
      return {DEL, r.t0, r.t1, r.fwd, lv - r.lv0, r.lv1 - r.lv0};
    }
    auto [lf, i] = ins_lookup(lv);
    const BEntry& e = lf->e[i];
    return {INS, e.ids, e.ide(), true, lv - e.ids, e.len};
  }

  static void rr_sub(i64 t0, i64 t1, bool fwd, i64 o0, i64 o1,
                     i64& lo, i64& hi) {
    if (fwd) { lo = t0 + o0; hi = t0 + o1; }
    else { lo = t1 - o1; hi = t1 - o0; }
  }

  void toggle_items(i64 s, i64 e, int mode) {
    // modes: 0 ins, 1 unins, 2 del, 3 undel
    del_cont_pos = -1;
    i64 lv = s;
    while (lv < e) {
      auto [lf, idx] = ins_lookup(lv);
      if (lv > lf->e[idx].ids) {
        auto [l2, i2] = split_entry(lf, idx, lv - lf->e[idx].ids);
        lf = l2; idx = i2 + 1;  // right half
      }
      if (e < lf->e[idx].ide()) {
        auto [l2, i2] = split_entry(lf, idx, e - lf->e[idx].ids);
        lf = l2; idx = i2;  // left half
      }
      BEntry& en = lf->e[idx];
      i64 len = en.len;
      i64 dcur = 0, dup = 0;
      switch (mode) {
        case 0: assert(en.state == 0); en.state = 1; dcur = len; break;
        case 1: assert(en.state == 1); en.state = 0; dcur = -len; break;
        case 2:
          assert(en.state >= 1);
          if (en.state == 1) dcur = -len;
          en.state += 1;
          if (!en.ever) { dup = -len; en.ever = true; }
          break;
        case 3:
          assert(en.state >= 2);
          en.state -= 1;
          if (en.state == 1) dcur = len;
          break;
      }
      bump(lf, 0, dcur, dup);
      lv = en.ide();
      try_merge_left(lf, idx);
    }
  }

#ifdef DT_CHECK
  // Deep invariant checker (debug builds): parent aggregates vs recomputed
  // child totals, linked-list order, and index coverage of every entry.
  void check_node(BNode* nd) const {
    for (int i = 0; i < nd->n; i++) {
      if (nd->leaf_children) {
        BLeaf* lf = (BLeaf*)nd->ch[i];
        assert(lf->parent == nd && lf->pslot == i);
        assert(lf->n > 0);
        i64 raw, cur, up;
        leaf_totals(lf, raw, cur, up);
        assert(nd->raw[i] == raw && nd->cur[i] == cur && nd->up[i] == up);
      } else {
        BNode* c = (BNode*)nd->ch[i];
        assert(c->parent == nd && c->pslot == i);
        i64 raw = 0, cur = 0, up = 0;
        for (int j = 0; j < c->n; j++) {
          raw += c->raw[j]; cur += c->cur[j]; up += c->up[j];
        }
        assert(nd->raw[i] == raw && nd->cur[i] == cur && nd->up[i] == up);
        check_node(c);
      }
    }
  }
  void check() const {
    check_node(root);
    // every entry reachable via the linked list maps to its leaf
    for (BLeaf* lf = first_leaf; lf; lf = lf->next) {
      assert(lf->n > 0);
      for (int i = 0; i < lf->n; i++) {
        assert(lf->e[i].len > 0);
        if (lf->e[i].ids < UNDERWATER) {
          assert(leaf_of[lf->e[i].ids - base] == lf);
          assert(leaf_of[lf->e[i].ide() - 1 - base] == lf);
        } else {
          assert(uw_index.query(lf->e[i].ids) == lf);
          assert(uw_index.query(lf->e[i].ide() - 1) == lf);
        }
      }
    }
  }
#endif

  void advance_by_range(Span rng) {
    g_events.advance_calls++;
    i64 start = rng.start, end = rng.end;
    while (start < end) {
      QueryRes q = index_query(start);
      i64 take = std::min(q.total - q.offset, end - start);
      i64 lo, hi;
      rr_sub(q.t0, q.t1, q.fwd, q.offset, q.offset + take, lo, hi);
      toggle_items(lo, hi, q.kind == INS ? 0 : 2);
      start += take;
    }
  }

  void retreat_by_range(Span rng) {
    g_events.retreat_calls++;
    i64 start = rng.start, end = rng.end;
    while (start < end) {
      i64 req = end - 1;
      QueryRes q = index_query(req);
      i64 chunk_start = req - q.offset;
      i64 s = std::max(start, chunk_start);
      i64 e = std::min(end, chunk_start + q.total);
      i64 o0 = s - chunk_start;
      i64 lo, hi;
      rr_sub(q.t0, q.t1, q.fwd, o0, o0 + (e - s), lo, hi);
      toggle_items(lo, hi, q.kind == INS ? 1 : 3);
      end -= e - s;
    }
  }
};

#ifdef DT_PROF
#include <x86intrin.h>
struct ProfCounters {
  unsigned long long diff = 0, walk_fr = 0, retreat = 0, advance = 0,
                     apply_ins = 0, apply_del = 0, emit_misc = 0, doc = 0,
                     conflict = 0;
} g_prof;
struct ProfScope {
  unsigned long long* tgt;
  unsigned long long t0;
  ProfScope(unsigned long long* t) : tgt(t), t0(__rdtsc()) {}
  ~ProfScope() { *tgt += __rdtsc() - t0; }
};
#define PROF(field) ProfScope _ps(&g_prof.field)
extern "C" void dt_prof_dump() {
  fprintf(stderr,
          "prof cycles: diff=%llu walk_fr=%llu retreat=%llu advance=%llu "
          "apply_ins=%llu apply_del=%llu emit_misc=%llu doc=%llu "
          "conflict=%llu\n",
          g_prof.diff, g_prof.walk_fr, g_prof.retreat, g_prof.advance,
          g_prof.apply_ins, g_prof.apply_del, g_prof.emit_misc, g_prof.doc,
          g_prof.conflict);
  fprintf(stderr,
          "diff calls=%ld iters=%ld local_iters=%ld walk steps=%ld "
          "zero=%ld orr_iters=%ld\n",
          g_diff_calls, g_diff_iters, g_diff_iters2, g_walk_steps,
          g_walk_zero, g_orr_iters);
  g_orr_iters = 0;
  g_diff_calls = g_diff_iters = g_diff_iters2 = g_walk_steps = g_walk_zero = 0;
  g_prof = ProfCounters{};
}
#else
#define PROF(field)
extern "C" void dt_prof_dump() {}
#endif


// ---------------------------------------------------------------- walker
//
// Conflict-zone walker over a LOCAL piece graph (the listmerge2
// "conflict subgraph" idea, reference src/listmerge2/conflict_subgraph.rs,
// applied to the M1 pipeline): the conflict + new-op spans are chopped at
// graph-entry boundaries AND at every parent reference, so every frontier
// that can arise during the walk is exactly a set of piece-ends. Diffs then
// run over int32 piece indices with a small binary heap instead of heap
// walks over the global graph. Because each step's diff moves the frontier
// exactly onto the consumed piece's parents, the frontier after each
// consume is the single head {piece}, so no global frontier maintenance is
// needed inside the walk (reference equivalent: txn_trace.rs:75-160).

struct Piece {
  Span span;
  int32_t pstart, np;   // local parents slice into Zone::lpar
  u8 np_global;          // parent count incl. out-of-zone (walk heuristic)
  u8 phase;              // 0 = conflict (seed tracker), 1 = new ops (emit)
  bool visited = false;
};

struct Zone {
  std::vector<Piece> pieces;       // ascending LV order
  std::vector<int32_t> lpar;       // flat local parent idxs
  std::vector<int32_t> cindptr, cflat;  // children CSR
  std::vector<int32_t> pending;    // unvisited local parent count
  int32_t last_head = -1;          // last consumed piece (shared across phases)
  // scratch for diff_local: active bitmap + per-piece flag; each piece
  // enters the working set at most once (parents always have lower idx),
  // flags combine in place instead of queueing duplicates.
  std::vector<uint64_t> abits;
  std::vector<u8> aflag;
  std::vector<int32_t> touched;

  // a, b: descending span lists (phase 0 / phase 1)
  Zone(const Graph& g, const std::vector<Span>& conflict,
       const std::vector<Span>& fresh) {
    // 1. merge into ascending (span, phase) list
    struct SP { Span s; u8 phase; };
    std::vector<SP> spans;
    spans.reserve(conflict.size() + fresh.size());
    {
      auto ia = conflict.rbegin(), ea = conflict.rend();
      auto ib = fresh.rbegin(), eb = fresh.rend();
      while (ia != ea || ib != eb) {
        if (ib == eb || (ia != ea && ia->start < ib->start))
          spans.push_back({*ia++, 0});
        else
          spans.push_back({*ib++, 1});
      }
    }
    // 2. chop at graph entry boundaries -> proto piece spans. The graph
    //    entry index only moves forward across the ascending spans, so
    //    one binary search per span (not per entry) suffices.
    struct Proto { Span s; u8 phase; bool entry_head; uint32_t gi; };
    std::vector<Proto> protos;
    protos.reserve(spans.size() * 2);
    for (const SP& sp : spans) {
      i64 start = sp.s.start, end = sp.s.end;
      size_t i = g.find_idx(start);
      while (start < end) {
        i64 t_end = std::min(g.ends[i], end);
        protos.push_back({{start, t_end}, sp.phase, start == g.starts[i],
                          (uint32_t)i});
        start = t_end;
        i++;
      }
    }
    // 3. collect split points: every parent reference p with p+1 strictly
    //    inside a piece forces a boundary at p+1. Candidates are bounded
    //    LVs, so a bitmap gives dedup + sorted extraction for free (no
    //    sort/unique/merge-join); p+1 strictly inside a proto implies p
    //    is inside the same proto, so one containment form suffices.
    i64 lv_base = protos.empty() ? 0 : protos.front().s.start;
    i64 lv_top = protos.empty() ? 0 : protos.back().s.end;
    // biased by lv_base so the bitmap is O(zone extent), not O(history):
    // an incremental tail merge must not zero-fill the whole LV space
    std::vector<uint64_t> cutbits((size_t)(lv_top - lv_base + 64) / 64, 0);
    for (const Proto& pr : protos) {
      if (!pr.entry_head) continue;  // mid-entry pieces: single parent start-1
      for (size_t k = 0; k < g.pn(pr.gi); k++) {
        i64 c = g.pb(pr.gi)[k] + 1 - lv_base;
        if (c > 0 && c < lv_top - lv_base)
          cutbits[c >> 6] |= 1ull << (c & 63);
      }
    }
    std::vector<i64> cuts;
    for (const Proto& pr : protos) {
      i64 s = pr.s.start - lv_base, e = pr.s.end - lv_base;
      for (i64 w = s >> 6; w <= (e - 1) >> 6; w++) {
        uint64_t bits = cutbits[w];
        while (bits) {
          int b = __builtin_ctzll(bits);
          bits &= bits - 1;
          i64 c = (w << 6) | b;
          if (c > s && c < e) cuts.push_back(c + lv_base);
        }
      }
    }
    // 4. final pieces (pgi carries each piece's graph entry from step 2,
    //    phead whether it starts that entry — saves re-searching in 5)
    pieces.reserve(protos.size() + cuts.size());
    std::vector<uint32_t> pgi;
    std::vector<u8> phead;
    pgi.reserve(protos.size() + cuts.size());
    phead.reserve(protos.size() + cuts.size());
    size_t ci = 0;
    for (const Proto& pr : protos) {
      while (ci < cuts.size() && cuts[ci] <= pr.s.start) ci++;
      i64 start = pr.s.start;
      bool head = pr.entry_head;
      size_t cj = ci;
      while (start < pr.s.end) {
        i64 end = pr.s.end;
        if (cj < cuts.size() && cuts[cj] < end) end = cuts[cj++];
        Piece p;
        p.span = {start, end};
        p.phase = pr.phase;
        p.np_global = head ? 2 : 1;  // refined below for true heads
        p.pstart = 0; p.np = 0;
        pieces.push_back(p);
        pgi.push_back(pr.gi);
        phead.push_back(head ? 1 : 0);
        start = end;
        head = false;
      }
    }
    // 5. local parents. Every in-zone parent reference lands on a piece's
    //    last LV (that is what the cuts guarantee), so a linear-probe
    //    hash of span.end-1 -> piece idx answers each lookup O(1) — the
    //    old per-parent binary search was the constructor's hot spot.
    size_t hbits = 3;
    while ((1u << hbits) < pieces.size() * 2) hbits++;
    const size_t hmask = (1u << hbits) - 1;
    std::vector<i64> hkey(hmask + 1, -2);   // -2: empty (LVs are >= 0)
    std::vector<int32_t> hval(hmask + 1);
    auto hput = [&](i64 key, int32_t val) {
      size_t h = ((uint64_t)key * 0x9E3779B97F4A7C15ull) >> (64 - hbits);
      while (hkey[h] != -2) h = (h + 1) & hmask;
      hkey[h] = key; hval[h] = val;
    };
    auto hget = [&](i64 key) -> int32_t {
      size_t h = ((uint64_t)key * 0x9E3779B97F4A7C15ull) >> (64 - hbits);
      while (hkey[h] != -2) {
        if (hkey[h] == key) return hval[h];
        h = (h + 1) & hmask;
      }
      return -1;
    };
    for (size_t i = 0; i < pieces.size(); i++)
      hput(pieces[i].span.end - 1, (int32_t)i);
    for (size_t i = 0; i < pieces.size(); i++) {
      Piece& p = pieces[i];
      size_t gi = pgi[i];
      p.pstart = (int32_t)lpar.size();
      if (phead[i]) {
        p.np_global = (u8)std::min<size_t>(g.pn(gi), 255);
        for (size_t k = 0; k < g.pn(gi); k++) {
          int32_t pi = hget(g.pb(gi)[k]);
          if (pi >= 0) lpar.push_back(pi);
        }
      } else {
        p.np_global = 1;
        int32_t pi = hget(p.span.start - 1);
        if (pi >= 0) lpar.push_back(pi);
      }
      p.np = (int32_t)(lpar.size() - p.pstart);
    }
    // 6. children CSR + pending counters
    cindptr.assign(pieces.size() + 1, 0);
    for (int32_t pi : lpar) cindptr[pi + 1]++;
    for (size_t i = 0; i < pieces.size(); i++) cindptr[i + 1] += cindptr[i];
    cflat.resize(lpar.size());
    {
      std::vector<int32_t> fill(cindptr.begin(), cindptr.end() - 1);
      for (size_t i = 0; i < pieces.size(); i++)
        for (int32_t k = 0; k < pieces[i].np; k++)
          cflat[fill[lpar[pieces[i].pstart + k]]++] = (int32_t)i;
    }
    pending.resize(pieces.size());
    for (size_t i = 0; i < pieces.size(); i++) pending[i] = pieces[i].np;
    abits.assign((pieces.size() + 63) / 64, 0);
    aflag.assign(pieces.size(), 0);
  }

  // diff between head closure and parents closure, over local idxs.
  // Appends descending piece idxs to retreat (head-only) / advance
  // (parents-only).
  void diff_local(int32_t head, const int32_t* par, int32_t np,
                  std::vector<int32_t>& retreat_i,
                  std::vector<int32_t>& advance_i) {
    enum : u8 { A = 0, B = 1, Shared = 2 };
    g_events.walk_steps++;
#ifdef DT_PROF
    extern long g_walk_steps, g_walk_zero, g_diff_iters2;
    g_walk_steps++;
    if (np == 1 && par[0] == head) g_walk_zero++;
#endif
    if (np == 1 && par[0] == head) return;  // zero-churn chain step
    int hi_word = -1;
    long nonshared = 0;
    touched.clear();
    auto bit_push = [&](int32_t idx, u8 flag) {
      int w = idx >> 6;
      uint64_t m = 1ull << (idx & 63);
      if (abits[w] & m) {
        u8 old = aflag[idx];
        if (old != Shared && old != flag) { aflag[idx] = Shared; nonshared--; }
      } else {
        abits[w] |= m;
        aflag[idx] = flag;
        touched.push_back(idx);
        if (flag != Shared) nonshared++;
        if (w > hi_word) hi_word = w;
      }
    };
    if (head >= 0) bit_push(head, A);
    for (int32_t k = 0; k < np; k++) bit_push(par[k], B);
    while (nonshared > 0) {
#ifdef DT_PROF
      g_diff_iters2++;
#endif
      while (abits[hi_word] == 0) hi_word--;
      int b = 63 - __builtin_clzll(abits[hi_word]);
      int32_t idx = (int32_t)((hi_word << 6) | b);
      abits[hi_word] &= ~(1ull << b);
      u8 flag = aflag[idx];
      if (flag != Shared) nonshared--;
      if (flag == A) retreat_i.push_back(idx);
      else if (flag == B) advance_i.push_back(idx);
      const Piece& p = pieces[idx];
      for (int32_t k = 0; k < p.np; k++) bit_push(lpar[p.pstart + k], flag);
    }
    // clear any bits left set by the early (all-Shared) exit
    for (int32_t idx : touched) abits[idx >> 6] &= ~(1ull << (idx & 63));
  }
};

struct Walker {
  Zone& z;
  u8 phase;
  std::vector<int32_t> to_process;
  std::vector<int32_t> retreat_i, advance_i;

  Walker(Zone& zone, u8 ph) : z(zone), phase(ph) {
    for (int i = (int)z.pieces.size() - 1; i >= 0; i--)
      if (z.pieces[i].phase == phase && !z.pieces[i].visited &&
          z.pending[i] == 0)
        to_process.push_back(i);
  }

  // returns false when done
  bool next(std::vector<Span>& retreat, std::vector<Span>& advance_rev,
            Span& consume) {
    if (to_process.empty()) return false;
    // reference heuristic (txn_trace.rs:240-258): defer merge pieces,
    // preferring the most recently readied non-merge piece
    int32_t idx = to_process.back();
    if (z.pieces[idx].np_global >= 2) {
      int found = -1;
      for (int ii = (int)to_process.size() - 1; ii >= 0; ii--) {
        if (z.pieces[to_process[ii]].np_global < 2) { found = ii; break; }
      }
      if (found >= 0) {
        idx = to_process[found];
        to_process[found] = to_process.back();
        to_process.pop_back();
      } else to_process.pop_back();
    } else to_process.pop_back();

    Piece& e = z.pieces[idx];
    e.visited = true;

    retreat.clear(); advance_rev.clear();
    { PROF(diff);
      retreat_i.clear(); advance_i.clear();
      z.diff_local(z.last_head, z.lpar.data() + e.pstart, e.np,
                   retreat_i, advance_i);
      for (int32_t i : retreat_i)
        push_reversed_rle(retreat, z.pieces[i].span);
      for (int32_t i : advance_i)
        push_reversed_rle(advance_rev, z.pieces[i].span);
    }
    z.last_head = idx;

    for (int32_t k = z.cindptr[idx]; k < z.cindptr[idx + 1]; k++) {
      int32_t c = z.cflat[k];
      if (--z.pending[c] == 0 && z.pieces[c].phase == phase)
        to_process.push_back(c);
    }
    consume = e.span;
    // Zero-churn chain coalescing: while the piece just readied is idx's
    // sole-parent successor with an LV-contiguous span (an entry run the
    // cut pass split, or a straight chain), fold it into this consume —
    // its diff would be empty and its frontier is just {predecessor}, so
    // skipping the per-piece scaffolding (diff, emit lookup, graph
    // advance) changes nothing observable.
    while (!to_process.empty()) {
      int32_t c = to_process.back();
      Piece& pc = z.pieces[c];
      if (pc.np != 1 || z.lpar[pc.pstart] != idx ||
          pc.span.start != consume.end)
        break;
      to_process.pop_back();
      pc.visited = true;
      g_events.walk_steps++;
      consume.end = pc.span.end;
      idx = c;
      z.last_head = c;
      for (int32_t k = z.cindptr[c]; k < z.cindptr[c + 1]; k++) {
        int32_t cc = z.cflat[k];
        if (--z.pending[cc] == 0 && z.pieces[cc].phase == phase)
          to_process.push_back(cc);
      }
    }
    return true;
  }
};

// ---------------------------------------------------------------- context

struct XfOp { i64 lv; i64 len; u8 kind; u8 fwd; i64 pos; };  // pos=-1 => gone

// Chunked int32 text buffer (the native rope; mirrors
// diamond_types_tpu/utils/rope.py).
struct TextBuf {
  static const size_t TARGET = 2048;
  static const size_t GROUP = 64;  // chunks per group-sum slot
  std::vector<std::vector<int32_t>> chunks;
  std::vector<i64> sizes;  // parallel to chunks
  std::vector<i64> gsum;   // per-group char totals (incremental index)
  i64 total = 0;

  TextBuf() { chunks.emplace_back(); sizes.push_back(0); gsum.push_back(0); }

  // O(#chunks); only needed when chunks are added/removed (split, erase)
  void rebuild_groups() {
    gsum.assign((chunks.size() + GROUP - 1) / GROUP, 0);
    for (size_t i = 0; i < chunks.size(); i++) gsum[i / GROUP] += sizes[i];
  }

  std::pair<size_t, i64> find(i64 pos) const {
    size_t g = 0;
    while (g + 1 < gsum.size() && pos >= gsum[g]) { pos -= gsum[g]; g++; }
    size_t i = g * GROUP;
    size_t end = std::min(chunks.size(), (g + 1) * GROUP);
    while (i + 1 < end && pos >= sizes[i]) { pos -= sizes[i]; i++; }
    return {i, pos};
  }

  void insert(i64 pos, const int32_t* s, i64 n) {
    if (n <= 0) return;
    auto [ci, off] = find(pos);
    auto& ch = chunks[ci];
    ch.insert(ch.begin() + off, s, s + n);
    sizes[ci] += n;
    gsum[ci / GROUP] += n;
    total += n;
    if (ch.size() > 2 * TARGET) {
      // split into TARGET-sized chunks
      std::vector<std::vector<int32_t>> parts;
      for (size_t i = 0; i < ch.size(); i += TARGET)
        parts.emplace_back(ch.begin() + i,
                           ch.begin() + std::min(ch.size(), i + TARGET));
      chunks.erase(chunks.begin() + ci);
      sizes.erase(sizes.begin() + ci);
      sizes.insert(sizes.begin() + ci, parts.size(), 0);
      for (size_t i = 0; i < parts.size(); i++)
        sizes[ci + i] = (i64)parts[i].size();
      chunks.insert(chunks.begin() + ci,
                    std::make_move_iterator(parts.begin()),
                    std::make_move_iterator(parts.end()));
      rebuild_groups();
    }
  }

  void erase(i64 pos, i64 n) {
    if (n <= 0) return;
    total -= n;
    auto [ci, off] = find(pos);
    bool removed = false;
    while (n > 0) {
      auto& ch = chunks[ci];
      i64 take = std::min((i64)ch.size() - off, n);
      ch.erase(ch.begin() + off, ch.begin() + off + take);
      sizes[ci] -= take;
      if (!removed) gsum[ci / GROUP] -= take;
      n -= take;
      if (ch.empty() && chunks.size() > 1) {
        chunks.erase(chunks.begin() + ci);
        sizes.erase(sizes.begin() + ci);
        removed = true;
      } else {
        ci++;
      }
      off = 0;
    }
    if (removed) rebuild_groups();
  }

  void dump(int32_t* out) const {
    i64 k = 0;
    for (const auto& ch : chunks) {
      std::memcpy(out + k, ch.data(), ch.size() * sizeof(int32_t));
      k += ch.size();
    }
  }
};

// ------------------------------------------------------------- composer
//
// Piece-table composer for the zone engine's host prep: composes one
// conflict-zone entry's sequential op stream into entry-start coordinates
// (a faithful port of diamond_types_tpu/listmerge/compose.py — see that
// module's docstring for the semantics; reference equivalent of the work
// it replaces: the per-op tracker origin scan, src/listmerge/merge.rs:
// 395-423). Treap over piece nodes in an index arena; the tree SHAPE may
// differ from the Python treap (priorities are independent randomness)
// but the in-order piece sequence — the only thing finish() reads — is
// identical.

using u64 = unsigned long long;
using u32 = unsigned int;

static const i64 COMP_BASE_INF = (i64)1 << 40;
static const u8 COMP_K_OWN = 1, COMP_K_LEFTJOIN = 2, COMP_K_ROOT = 3;

struct CompPiece {
  i64 base;      // >= 0: snapshot chars [base, base+length); -1: own chars
  i64 lv;        // own chars [lv, lv+length)
  i64 length;
  int headi;     // own: index into Composer::heads (governing run head)
  u64 prio;
  int l, r, up;
  i64 sub_alive;
  bool alive;
};

struct CompHead {
  u8 kind;        // COMP_K_*
  i64 anchor_lv;  // own-char anchor (K_OWN parent / K_LEFTJOIN parent)
  int q;          // query idx (K_LEFTJOIN ol / K_ROOT), else -1
  int block;      // block id the run belongs to
  i64 orr_own;    // own-char origin-right lv, or -1 = the block's B
  i64 head_lv;    // the run head char's own lv
};

// One entry's composition result (mirror of compose.ComposedEntry).
struct ComposedOut {
  std::vector<i64> q_cursor;
  std::vector<i64> ch_lv, ch_anchor, ch_headlv, ch_orrown;
  std::vector<int32_t> ch_block, ch_q;
  std::vector<u8> ch_head, ch_kind;
  std::vector<int32_t> blk_root_q, blk_start, blk_len;
  std::vector<i64> blk_root_lv;
  std::vector<i64> db0, db1, do0, do1;  // del_base / del_own pairs
};

struct Composer {
  std::vector<CompPiece> A;
  std::vector<CompHead> heads;
  int root = -1;
  u64 prio_state = 0x9E3779B97F4A7C15ull;
  std::vector<i64> q_cursor;
  int n_blocks = 0;
  std::vector<i64> blk_root_lv_all;   // block id -> root head char lv
  std::vector<int> blk_root_headi;    // block id -> root head meta idx
  std::vector<std::pair<i64, i64>> del_base, del_own;
  bool failed = false;

  Composer(bool with_base) {
    if (with_base) {
      A.push_back({0, -1, COMP_BASE_INF, -1, next_prio(), -1, -1, -1,
                   COMP_BASE_INF, true});
      root = 0;
    }
  }

  u64 next_prio() {   // splitmix64
    prio_state += 0x9E3779B97F4A7C15ull;
    u64 z = prio_state;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }

  inline void upd(int n) {
    CompPiece& p = A[n];
    i64 s = p.alive ? p.length : 0;
    if (p.l >= 0) s += A[p.l].sub_alive;
    if (p.r >= 0) s += A[p.r].sub_alive;
    p.sub_alive = s;
  }

  void fix_up(int n) { while (n >= 0) { upd(n); n = A[n].up; } }

  void rot_up(int x) {
    int p = A[x].up, g = A[p].up;
    if (A[p].l == x) {
      A[p].l = A[x].r;
      if (A[p].l >= 0) A[A[p].l].up = p;
      A[x].r = p;
    } else {
      A[p].r = A[x].l;
      if (A[p].r >= 0) A[A[p].r].up = p;
      A[x].l = p;
    }
    A[p].up = x;
    A[x].up = g;
    if (g >= 0) { if (A[g].l == p) A[g].l = x; else A[g].r = x; }
    else root = x;
    upd(p);
    upd(x);
  }

  void bubble(int x) {
    while (A[x].up >= 0 && A[A[x].up].prio < A[x].prio) rot_up(x);
    if (A[x].up < 0) root = x; else fix_up(A[x].up);
  }

  void insert_after(int a, int x) {
    if (a < 0) {
      int n = root;
      if (n < 0) { root = x; return; }
      while (A[n].l >= 0) n = A[n].l;
      A[n].l = x;
      A[x].up = n;
    } else if (A[a].r < 0) {
      A[a].r = x;
      A[x].up = a;
    } else {
      int n = A[a].r;
      while (A[n].l >= 0) n = A[n].l;
      A[n].l = x;
      A[x].up = n;
    }
    fix_up(A[x].up);
    bubble(x);
  }

  int succ(int n) const {
    if (A[n].r >= 0) {
      n = A[n].r;
      while (A[n].l >= 0) n = A[n].l;
      return n;
    }
    while (A[n].up >= 0 && A[A[n].up].r == n) n = A[n].up;
    return A[n].up;
  }

  int leftmost() const {
    int n = root;
    if (n < 0) return -1;
    while (A[n].l >= 0) n = A[n].l;
    return n;
  }

  // (piece, offset) of visible char pos; piece < 0 on out-of-range
  std::pair<int, i64> find_visible(i64 pos) const {
    int n = root;
    while (n >= 0) {
      const CompPiece& p = A[n];
      i64 la = p.l >= 0 ? A[p.l].sub_alive : 0;
      if (pos < la) n = p.l;
      else if (p.alive && pos < la + p.length) return {n, pos - la};
      else { pos -= la + (p.alive ? p.length : 0); n = p.r; }
    }
    return {-1, 0};
  }

  int split(int n, i64 off) {
    int right;
    CompPiece& p0 = A[n];
    if (p0.base >= 0)
      A.push_back({p0.base + off, -1, p0.length - off, -1, next_prio(),
                   -1, -1, -1, 0, p0.alive});
    else
      A.push_back({-1, p0.lv + off, p0.length - off, p0.headi, next_prio(),
                   -1, -1, -1, 0, p0.alive});
    right = (int)A.size() - 1;
    A[right].sub_alive = A[right].alive ? A[right].length : 0;
    A[n].length = off;
    fix_up(n);
    insert_after(n, right);
    return right;
  }

  int emit_query(int prev) {
    // query gap must follow a snapshot piece (or doc start)
    if (prev >= 0 && A[prev].base < 0) { failed = true; return -1; }
    q_cursor.push_back(prev < 0 ? 0 : A[prev].base + A[prev].length);
    return (int)q_cursor.size() - 1;
  }

  void insert(i64 pos, i64 lv, i64 length) {
    int prev;
    if (pos == 0) prev = -1;
    else {
      auto [node, off] = find_visible(pos - 1);
      if (node < 0) { failed = true; return; }
      if (off + 1 < A[node].length) split(node, off + 1);
      prev = node;
    }
    int nxt = prev >= 0 ? succ(prev) : leftmost();
    i64 orr_own = (nxt >= 0 && A[nxt].base < 0) ? A[nxt].lv : -1;
    int headi = (int)heads.size();
    if (prev >= 0 && A[prev].base < 0) {
      // ol is an own char: right child of it (K_OWN)
      i64 anchor = A[prev].lv + A[prev].length - 1;
      heads.push_back({COMP_K_OWN, anchor, -1, heads[A[prev].headi].block,
                       orr_own, lv});
    } else if (nxt >= 0 && A[nxt].base < 0) {
      // ol snapshot/doc-start, next piece own: left-join that block
      int q = emit_query(prev);
      heads.push_back({COMP_K_LEFTJOIN, A[nxt].lv, q,
                       heads[A[nxt].headi].block, orr_own, lv});
    } else {
      int q = emit_query(prev);
      int blk = n_blocks++;
      blk_root_lv_all.push_back(lv);
      blk_root_headi.push_back(headi);
      heads.push_back({COMP_K_ROOT, -1, q, blk, -1, lv});
    }
    A.push_back({-1, lv, length, headi, next_prio(), -1, -1, -1,
                 length, true});
    insert_after(prev, (int)A.size() - 1);
  }

  void del(i64 pos, i64 length) {
    auto [node, off] = find_visible(pos);
    if (node < 0) { failed = true; return; }
    if (off > 0) node = split(node, off);
    i64 remaining = length;
    while (remaining > 0) {
      if (node < 0) { failed = true; return; }  // delete past end
      if (!A[node].alive) { node = succ(node); continue; }
      i64 take = std::min(remaining, A[node].length);
      if (take < A[node].length) split(node, take);
      if (A[node].base >= 0)
        del_base.emplace_back(A[node].base, A[node].base + take);
      else
        del_own.emplace_back(A[node].lv, A[node].lv + take);
      A[node].alive = false;
      fix_up(node);
      remaining -= take;
      node = succ(node);
    }
  }

  void finish(ComposedOut& out) {
    out.q_cursor = std::move(q_cursor);
    for (auto& d : del_base) { out.db0.push_back(d.first);
                               out.db1.push_back(d.second); }
    for (auto& d : del_own)  { out.do0.push_back(d.first);
                               out.do1.push_back(d.second); }
    // in-order walk collecting own pieces grouped by block id;
    // intra-block order IS table order
    struct PBRow { i64 lv, len; int headi; };
    std::vector<std::vector<PBRow>> pb(n_blocks);
    {
      std::vector<int> st;
      int cur = root;
      while (!st.empty() || cur >= 0) {
        while (cur >= 0) { st.push_back(cur); cur = A[cur].l; }
        cur = st.back();
        st.pop_back();
        const CompPiece& p = A[cur];
        if (p.base < 0)
          pb[heads[p.headi].block].push_back({p.lv, p.length, p.headi});
        cur = p.r;
      }
    }
    for (int blk = 0; blk < n_blocks; blk++) {
      if (pb[blk].empty()) continue;   // dense output block reindex
      int bi = (int)out.blk_start.size();
      i64 total = out.ch_lv.size();
      i64 blen = 0;
      for (auto& t : pb[blk]) blen += t.len;
      out.blk_start.push_back((int32_t)total);
      out.blk_len.push_back((int32_t)blen);
      out.blk_root_lv.push_back(blk_root_lv_all[blk]);
      out.blk_root_q.push_back(heads[blk_root_headi[blk]].q);
      for (auto& t : pb[blk]) {
        i64 lv = t.lv, ln = t.len;
        const CompHead& h = heads[t.headi];
        for (i64 k = 0; k < ln; k++) {
          i64 clv = lv + k;
          bool is_head = clv == h.head_lv;
          out.ch_lv.push_back(clv);
          out.ch_block.push_back(bi);
          out.ch_headlv.push_back(h.head_lv);
          out.ch_orrown.push_back(h.orr_own);
          out.ch_head.push_back(is_head ? 1 : 0);
          out.ch_kind.push_back(is_head ? h.kind : 0);
          out.ch_anchor.push_back(is_head ? h.anchor_lv : -1);
          out.ch_q.push_back(is_head ? h.q : -1);
        }
      }
    }
  }
};

namespace zonepack {

struct Step {
  int32_t op, a, b, snap;
  std::vector<std::array<int32_t, 5>> blocks;  // cursor, prev, root, start, len
  std::vector<std::array<int32_t, 7>> chars;   // slot, ol_s, ol_c, orr, blk, ag, sq
  std::vector<std::array<int32_t, 3>> dels;    // kind, a, b
};

struct PackState {
  std::vector<Step> steps;
  i64 MB, MC, MD;
  Step* cur = nullptr;

  Step* new_step(int32_t op, int32_t a, int32_t b, int32_t snap) {
    steps.push_back(Step{op, a, b, snap, {}, {}, {}});
    cur = &steps.back();
    return cur;
  }
};

}  // namespace zonepack

struct Ctx {
  Graph g;
  Agents aa;
  Ops ops;
  std::vector<int32_t> ins_arena;
  TextBuf doc;
  std::vector<i64> version;
  std::vector<XfOp> out;
  std::vector<i64> out_frontier;
  // kept after transform for dt_dump_tracker (device-linearizer oracle)
  std::unique_ptr<Tracker> last_tracker;
  // conflict zone's common-ancestor frontier (the version whose document
  // the tracker's underwater id space tiles)
  std::vector<i64> zone_common;
  // collisions of the LAST transform (survives release_tracker)
  i64 last_collisions = 0;
  // dt_zone_pack's two-call fetch buffer
  std::vector<zonepack::Step> pack_steps;
  // compose-cache identity: bumped by every dt_compose_plan; the packer
  // validates it so a cache from a DIFFERENT plan (same entry count)
  // can never be packed silently
  i64 compose_serial = 0;
  // dt_merge_into_doc's zone-everything mode (from=[] merging onto an
  // empty doc): transform skips FF so the WHOLE history walks the zone
  // and the final doc assembles straight from the tracker in one leaf
  // pass — no per-op rope surgery, no out-row recording. FF's
  // untransformed emission and the tracker walk produce the same
  // document; this trades a little extra integrate work on the linear
  // prefix (tiny on the shipped corpora) for dropping the rope phase.
  bool merge_no_ff = false;
  // last dt_compose_plan / dt_compose_linear results
  std::vector<ComposedOut> composed;
  std::vector<std::pair<i64, i64>> linear_pieces;
  // transform() metadata for dt_merge_into_doc's fast doc assembly:
  // out[0..ff_split) are the FF-mode untransformed ops; zone_ff_base is
  // true when the conflict zone's phase-0 seed set was empty (every
  // forward merge / checkout), i.e. the underwater id space tiles
  // exactly the rope state after the FF ops.
  size_t ff_split = 0;
  bool zone_ff_base = false;
  // last dt_encode_full result
  std::vector<u8> enc_buf;
};

// Feed one span's op runs through a composer (mirror of
// compose.compose_entry's iter_range loop). False on unsupported input
// (reverse insert runs — matches reference merge.rs:384 unimplemented!).
static bool compose_span_ops(Ctx* c, Composer& comp, Span span) {
  Ops& ops = c->ops;
  if (span_empty(span)) return true;
  size_t i = ops.find_idx(span.start);
  i64 pos = span.start;
  while (pos < span.end) {
    const OpRun& run = ops.runs[i];
    i64 run_end = run.lv + (run.end - run.start);
    i64 o0 = pos - run.lv;
    i64 o1 = std::min(span.end, run_end) - run.lv;
    OpRun piece = Ops::slice(run, o0, o1);
    i64 plen = piece.end - piece.start;
    if (piece.kind == INS) {
      if (!piece.fwd) return false;
      comp.insert(piece.start, piece.lv, plen);
    } else {
      comp.del(piece.start, plen);
    }
    if (comp.failed) return false;
    pos = run.lv + o1;
    i++;
  }
  return true;
}

static void emit_ops_range(Ctx* c, Tracker& tracker, Span consume,
                           bool emit) {
  Ops& ops = c->ops;
  if (span_empty(consume)) return;
  size_t i = ops.find_idx(consume.start);
  i64 pos = consume.start;
  while (pos < consume.end) {
    const OpRun& run = ops.runs[i];
    i64 run_end = run.lv + (run.end - run.start);
    i64 o0 = pos - run.lv;
    i64 o1 = std::min(consume.end, run_end) - run.lv;
    OpRun piece = Ops::slice(run, o0, o1);
    // apply in chunks bounded by agent runs; the agent lookup is hoisted
    // across entry-bounded chunks of the same run (alen counts down)
    i64 agent = -1, alen = 0;
    while (true) {
      i64 plen = piece.end - piece.start;
      if (alen <= 0) {
        i64 seq;
        c->aa.local_to_agent(piece.lv, agent, seq);
        alen = c->aa.span_len(piece.lv, plen);
      }
      std::pair<i64,i64> r;
      if (piece.kind == INS) { PROF(apply_ins); g_events.apply_ins_runs++; r = tracker.apply(c->aa, agent, piece, alen); }
      else { PROF(apply_del); g_events.apply_del_runs++; r = tracker.apply(c->aa, agent, piece, alen); }
      auto [consumed, xf] = r;
#ifdef DT_CHECK
      fprintf(stderr, "applied lv=%lld len=%lld kind=%d\n",
              (long long)piece.lv, (long long)consumed, (int)piece.kind);
      tracker.check();
#endif
      if (emit && !c->merge_no_ff)
        c->out.push_back({piece.lv, consumed, piece.kind, piece.fwd, xf});
      alen -= consumed;
      if (consumed == plen) break;
      piece = Ops::slice(piece, consumed, plen);
    }
    pos = run.lv + o1;
    i++;
  }
}

static void transform(Ctx* c, std::vector<i64> from, std::vector<i64> merge) {
  c->out.clear();
  c->last_tracker.reset();
  c->last_collisions = 0;
  c->ff_split = 0;
  c->zone_ff_base = false;
  std::vector<Span> new_ops, conflict_ops;
  { PROF(conflict);
    if (from.empty() && merge == c->g.heads) {
      // trivial checkout (the complex/merge bench shape): everything
      // reachable from the full frontier is OnlyB in one span — skip
      // the whole heap walk
      if (!c->g.ends.empty()) new_ops.push_back({0, c->g.ends.back()});
      c->zone_common.clear();
    } else {
      c->zone_common = c->g.find_conflicting(
          from, merge, [&](Span s, u8 flag) {
            push_reversed_rle(flag == Graph::OnlyB ? new_ops : conflict_ops,
                              s);
          });
    }
  }

  std::vector<i64> next_frontier = from;
  bool did_ff = false;

  // FF mode
  std::vector<i64> ps;
  while (!c->merge_no_ff && !new_ops.empty()) {
    Span span = new_ops.back();
    size_t i = c->g.find_idx(span.start);
    c->g.parents_at(span.start, ps);
    if (ps != next_frontier) break;
    new_ops.pop_back();
    i64 take_end = std::min(c->g.ends[i], span.end);
    if (take_end < span.end) new_ops.push_back({take_end, span.end});
    next_frontier.assign(1, take_end - 1);
    did_ff = true;
    // emit untransformed
    Ops& ops = c->ops;
    size_t oi = ops.find_idx(span.start);
    i64 pos = span.start;
    while (pos < take_end) {
      const OpRun& run = ops.runs[oi];
      i64 run_end = run.lv + (run.end - run.start);
      i64 o1 = std::min(take_end, run_end) - run.lv;
      OpRun piece = Ops::slice(run, pos - run.lv, o1);
      c->out.push_back({piece.lv, piece.end - piece.start, piece.kind,
                        piece.fwd, piece.start});
      pos = run.lv + o1;
      oi++;
    }
  }

  c->ff_split = c->out.size();
  if (!new_ops.empty()) {
    if (did_ff) {
      conflict_ops.clear();
      c->zone_common = c->g.find_conflicting(
          next_frontier, merge, [&](Span s, u8 flag) {
            if (flag != Graph::OnlyB) push_reversed_rle(conflict_ops, s);
          });
    }
    c->zone_ff_base = conflict_ops.empty();

    i64 ops_top = 0;
    if (!c->ops.runs.empty()) {
      const OpRun& lr = c->ops.runs.back();
      ops_top = lr.lv + (lr.end - lr.start);
    }
    i64 zone_base = ops_top;
    for (const Span& s : conflict_ops) zone_base = std::min(zone_base, s.start);
    for (const Span& s : new_ops) zone_base = std::min(zone_base, s.start);
    c->last_tracker.reset(new Tracker(zone_base, ops_top));
    Tracker& tracker = *c->last_tracker;
    std::unique_ptr<Zone> zp;
    { PROF(emit_misc); zp.reset(new Zone(c->g, conflict_ops, new_ops)); }
    Zone& zone = *zp;
    // build tracker over conflict set
    {
      Walker w(zone, 0);
      std::vector<Span> retreat, advance_rev;
      Span consume;
      while (w.next(retreat, advance_rev, consume)) {
        { PROF(retreat);
          for (const Span& s : retreat) tracker.retreat_by_range(s); }
        { PROF(advance);
          for (auto it = advance_rev.rbegin(); it != advance_rev.rend(); ++it)
            tracker.advance_by_range(*it); }
        emit_ops_range(c, tracker, consume, false);
      }
      // walk new ops
      Walker w2(zone, 1);
      while (w2.next(retreat, advance_rev, consume)) {
        { PROF(retreat);
          for (const Span& s : retreat) tracker.retreat_by_range(s); }
        { PROF(advance);
          for (auto it = advance_rev.rbegin(); it != advance_rev.rend(); ++it)
            tracker.advance_by_range(*it); }
        c->g.advance(next_frontier, consume);
        emit_ops_range(c, tracker, consume, true);
      }
    }
    c->last_collisions = tracker.collisions;
  }
  c->out_frontier = next_frontier;
}

// ---------------------------------------------------------------- encoder
//
// Native v1 writer — full snapshots AND patches (mirror of
// encoding/encode.py encode_oplog; format spec: /root/reference/
// BINARY.md, reference writer src/list/encoding/encode_oplog.rs
// `encode` + `encode_from`). The txn walk below (StWalk) mirrors the
// Python SpanningTreeWalker's traversal ORDER exactly, so the native
// output is BYTE-identical to the Python writer's — pinned by
// tests/test_encode.py.

// Exact order mirror of listmerge/walker.py SpanningTreeWalker
// (reference: src/listmerge/txn_trace.rs:75-332), track_frontier=False
// shape: yields (consume) spans only. The Zone walker's cut refinement
// produces a different (equally causal) order; the encoders use THIS
// one because byte parity with the Python writer requires the same
// traversal.
struct StWalk {
  struct Ent {
    Span span;
    int np_global;
    std::vector<int32_t> par, child;
    bool visited = false;
  };
  std::vector<Ent> input;
  std::vector<int32_t> to_process;

  int find_ent(i64 t) const {
    int lo = 0, hi = (int)input.size();
    while (lo < hi) {
      int mid = (lo + hi) / 2;
      if (t < input[mid].span.start) hi = mid;
      else if (t >= input[mid].span.end) lo = mid + 1;
      else return mid;
    }
    return -1;
  }

  // rev_spans: descending span list (diff_rev output order)
  StWalk(const Graph& g, const std::vector<Span>& rev_spans) {
    std::vector<i64> ps;
    for (auto it = rev_spans.rbegin(); it != rev_spans.rend(); ++it) {
      i64 start = it->start, end = it->end;
      size_t i = g.find_idx(start);
      while (start < end) {
        i64 t_end = std::min(g.ends[i], end);
        Ent e;
        e.span = {start, t_end};
        g.parents_at(start, ps);
        e.np_global = (int)ps.size();
        for (i64 p : ps) {
          int pi = find_ent(p);
          if (pi >= 0) e.par.push_back((int32_t)pi);
        }
        if (e.par.empty()) to_process.push_back((int32_t)input.size());
        input.push_back(std::move(e));
        start = t_end;
        i++;
      }
    }
    for (size_t i = 0; i < input.size(); i++)
      for (int32_t p : input[i].par)
        input[(size_t)p].child.push_back((int32_t)i);
    std::reverse(to_process.begin(), to_process.end());
  }

  bool next(Span& consume) {
    if (to_process.empty()) return false;
    // prefer non-merge entries, most recently readied (walker.py
    // __next__ / txn_trace.rs:243-265)
    int32_t idx = to_process.back();
    if (input[(size_t)idx].np_global >= 2) {
      int found = -1;
      for (int ii = (int)to_process.size() - 1; ii >= 0; ii--)
        if (input[(size_t)to_process[ii]].np_global < 2) { found = ii;
          break; }
      if (found >= 0) {
        idx = to_process[(size_t)found];
        to_process[(size_t)found] = to_process.back();
        to_process.pop_back();
      } else {
        to_process.pop_back();
      }
    } else {
      to_process.pop_back();
    }
    Ent& e = input[(size_t)idx];
    e.visited = true;
    for (int32_t ci : e.child) {
      Ent& ce = input[(size_t)ci];
      if (ce.visited) continue;
      bool ready = true;
      for (int32_t p : ce.par)
        if (!input[(size_t)p].visited) { ready = false; break; }
      if (ready) to_process.push_back(ci);
    }
    consume = e.span;
    return true;
  }
};

extern "C" i64 dt_lz4_compress(const u8* src, i64 n, u8* out, i64 cap);
extern "C" i64 dt_crc32c(const u8* data, i64 n, i64 seed);

namespace enc {

static const u64 CH_FILEINFO = 1, CH_DOCID = 2, CH_AGENTNAMES = 3,
                 CH_USERDATA = 4, CH_COMPRESSED = 5, CH_STARTBRANCH = 10,
                 CH_VERSION = 12,
                 CH_CONTENT_COMPRESSED = 14, CH_PATCHES = 20,
                 CH_OP_VERSIONS = 21, CH_OP_TYPE_POS = 22,
                 CH_OP_PARENTS = 23, CH_PATCH_CONTENT = 24,
                 CH_CONTENT_KNOWN = 25, CH_CRC = 100;
static const u64 DATA_PLAIN_TEXT = 4;

struct Buf {
  std::vector<u8> b;
  void leb(u64 v) {
    do { u8 x = v & 0x7f; v >>= 7; b.push_back(v ? (u8)(x | 0x80) : x); }
    while (v);
  }
  void raw(const u8* p, size_t n) { b.insert(b.end(), p, p + n); }
  void chunk(u64 type, const std::vector<u8>& data) {
    leb(type); leb(data.size()); raw(data.data(), data.size());
  }
  void utf8(int32_t cp) {
    u32 c = (u32)cp;
    if (c < 0x80) b.push_back((u8)c);
    else if (c < 0x800) {
      b.push_back((u8)(0xC0 | (c >> 6)));
      b.push_back((u8)(0x80 | (c & 0x3F)));
    } else if (c < 0x10000) {
      b.push_back((u8)(0xE0 | (c >> 12)));
      b.push_back((u8)(0x80 | ((c >> 6) & 0x3F)));
      b.push_back((u8)(0x80 | (c & 0x3F)));
    } else {
      b.push_back((u8)(0xF0 | (c >> 18)));
      b.push_back((u8)(0x80 | ((c >> 12) & 0x3F)));
      b.push_back((u8)(0x80 | ((c >> 6) & 0x3F)));
      b.push_back((u8)(0x80 | (c & 0x3F)));
    }
  }
};

static inline u64 mix(u64 v, bool bit) { return (v << 1) | (bit ? 1 : 0); }
static inline u64 zz(i64 v) { return mix(v < 0 ? -v : v, v < 0); }

// One op run in the type/position column (encode.py _write_op).
static void write_op(Buf& out, u8 kind, i64 start, i64 end, bool fwd,
                     i64& cursor) {
  i64 length = end - start;
  fwd = fwd || length == 1;
  i64 op_start = (kind == DEL && !fwd) ? end : start;
  i64 op_end = (kind == INS && fwd) ? end : start;
  i64 diff = op_start - cursor;
  cursor = op_end;
  u64 n;
  if (length != 1) {
    n = (u64)length;
    if (kind == DEL) n = mix(n, fwd);
  } else if (diff != 0) {
    n = zz(diff);
  } else {
    n = 0;
  }
  n = mix(n, kind == DEL);
  n = mix(n, diff != 0);
  n = mix(n, length != 1);
  out.leb(n);
  if (length != 1 && diff != 0) out.leb(zz(diff));
}

}  // namespace enc

static i64 encode_impl(Ctx* c, const u8* docid, i64 docid_len,
                       const u8* userdata, i64 ud_len, bool store_ins,
                       bool compress, const std::vector<i64>& from_version) {
  using namespace enc;
  Graph& g = c->g;
  Agents& aa = c->aa;
  Ops& ops = c->ops;
  i64 top = 0;
  if (!ops.runs.empty()) {
    const OpRun& lr = ops.runs.back();
    top = lr.lv + (lr.end - lr.start);
  }

  // file-local agent numbering, 1-based, in order of first use
  std::vector<int> agent_map(aa.names.size(), 0);
  std::vector<i64> seq_cursor(aa.names.size(), 0);
  int next_agent = 1;
  Buf names_buf;
  auto map_agent = [&](i64 agent) -> int {
    int& m = agent_map[(size_t)agent];
    if (m == 0) {
      m = next_agent++;
      const std::string& nm = aa.names[(size_t)agent];
      names_buf.leb(nm.size());
      names_buf.raw((const u8*)nm.data(), nm.size());
    }
    return m;
  };

  Buf agent_chunk;
  // pending agent run: mapped, delta, n, agent, seq_end
  bool aa_pending = false;
  int pa_m = 0;
  i64 pa_delta = 0, pa_n = 0, pa_agent = 0, pa_seq_end = 0;
  auto flush_aa = [&]() {
    if (!aa_pending) return;
    agent_chunk.leb(mix((u64)pa_m, pa_delta != 0));
    agent_chunk.leb((u64)pa_n);
    if (pa_delta != 0) agent_chunk.leb(zz(pa_delta));
    aa_pending = false;
  };

  Buf ops_chunk;
  i64 ops_cursor = 0;
  bool op_pending = false;
  OpRun pend{};
  auto flush_op = [&]() {
    if (!op_pending) return;
    write_op(ops_chunk, pend.kind, pend.start, pend.end, pend.fwd,
             ops_cursor);
    op_pending = false;
  };

  // INS content column: utf8 chars + (len, known) RLE runs
  Buf ins_text;
  std::vector<std::pair<i64, bool>> ins_runs;
  bool ins_any = false;
  auto push_content = [&](const OpRun& piece) {
    ins_any = true;
    bool known = piece.cp >= 0;
    i64 n = piece.end - piece.start;
    if (known)
      for (i64 k = 0; k < n; k++)
        ins_text.utf8(c->ins_arena[(size_t)(piece.cp + k)]);
    if (!ins_runs.empty() && ins_runs.back().second == known)
      ins_runs.back().first += n;
    else
      ins_runs.emplace_back(n, known);
  };

  Buf txns_chunk;
  // local span start -> output start (ascending by local start)
  std::vector<i64> map_ls, map_os, map_n;
  i64 next_output = 0;
  auto map_local = [&](i64 p) -> i64 {
    size_t i = (size_t)(std::upper_bound(map_ls.begin(), map_ls.end(), p) -
                        map_ls.begin());
    if (i == 0) return -1;
    i--;
    if (p >= map_ls[i] + map_n[i]) return -1;
    return map_os[i] + (p - map_ls[i]);
  };
  std::vector<i64> ps;
  auto write_txn = [&](Span span) {
    i64 n = span.end - span.start;
    i64 out_start = next_output;
    size_t at = (size_t)(std::upper_bound(map_ls.begin(), map_ls.end(),
                                          span.start) - map_ls.begin());
    map_ls.insert(map_ls.begin() + at, span.start);
    map_os.insert(map_os.begin() + at, out_start);
    map_n.insert(map_n.begin() + at, n);
    next_output += n;
    txns_chunk.leb((u64)n);
    g.parents_at(span.start, ps);
    if (ps.empty()) { txns_chunk.leb(1); return; }  // foreign-ROOT marker
    for (size_t i = 0; i < ps.size(); i++) {
      bool has_more = i + 1 < ps.size();
      i64 mapped = map_local(ps[i]);
      if (mapped >= 0) {
        txns_chunk.leb(mix(mix((u64)(out_start - mapped), has_more), false));
      } else {
        i64 agent, seq;
        aa.local_to_agent(ps[i], agent, seq);
        txns_chunk.leb(mix(mix((u64)map_agent(agent), has_more), true));
        txns_chunk.leb((u64)seq);
      }
    }
  };

  // ---- main walk: spans above from_version, SpanningTreeWalker order
  std::vector<Span> walk_spans;
  if (from_version.empty()) {
    if (top > 0) walk_spans.push_back({0, top});
  } else {
    std::vector<Span> only_a;
    g.diff_rev(from_version, g.heads, only_a, walk_spans);
    if (!only_a.empty()) return -2;  // from_version not an ancestor
  }
  {
    StWalk w(g, walk_spans);
    Span consume;
    while (w.next(consume)) {
      if (span_empty(consume)) continue;
      // 1. agent assignment runs
      i64 pos = consume.start;
      while (pos < consume.end) {
        i64 agent, seq;
        aa.local_to_agent(pos, agent, seq);
        i64 n = aa.span_len(pos, consume.end - pos);
        int m = map_agent(agent);
        if (aa_pending && pa_m == m && pa_seq_end == seq) {
          pa_n += n;
          pa_seq_end = seq + n;
          seq_cursor[(size_t)pa_agent] = seq + n;
        } else {
          flush_aa();
          i64 delta = seq - seq_cursor[(size_t)agent];
          seq_cursor[(size_t)agent] = seq + n;
          aa_pending = true;
          pa_m = m; pa_delta = delta; pa_n = n; pa_agent = agent;
          pa_seq_end = seq + n;
        }
        pos += n;
      }
      // 2. ops + content
      size_t oi = ops.find_idx(consume.start);
      pos = consume.start;
      while (pos < consume.end) {
        const OpRun& run = ops.runs[oi];
        i64 run_end = run.lv + (run.end - run.start);
        i64 o1 = std::min(consume.end, run_end) - run.lv;
        OpRun piece = Ops::slice(run, pos - run.lv, o1);
        if (piece.kind == INS && store_ins) push_content(piece);
        i64 plen = piece.end - piece.start;
        i64 pdlen = pend.end - pend.start;
        bool appendable = false;
        if (op_pending && pend.kind == piece.kind) {
          // RLE append rule (op.py can_append_ops / op_metrics.rs:235)
          if ((pdlen == 1 || pend.fwd) && (plen == 1 || piece.fwd)) {
            if (piece.kind == INS && piece.start == pend.end)
              appendable = true;
            if (piece.kind == DEL && piece.start == pend.start)
              appendable = true;
          }
          if (!appendable && piece.kind == DEL &&
              (pdlen == 1 || !pend.fwd) && (plen == 1 || !piece.fwd) &&
              piece.end == pend.start)
            appendable = true;
        }
        if (appendable) {  // op.py append_ops
          bool fwd = piece.start >= pend.start &&
                     (piece.start != pend.start || piece.kind == DEL);
          pend.fwd = fwd;
          if (piece.kind == DEL && !fwd) pend.start = piece.start;
          else pend.end += plen;
        } else {
          flush_op();
          op_pending = true;
          pend = piece;
        }
        pos = run.lv + o1;
        oi++;
      }
      // 3. parents
      write_txn(consume);
    }
  }
  flush_aa();
  flush_op();

  // ---- assemble ----
  std::vector<u8> compress_blob;
  bool have_compressed_chunk = false;
  Buf patches;
  if (store_ins && ins_any) {
    Buf body;
    body.leb(0);  // kind = INS
    if (compress) {
      have_compressed_chunk = true;
      Buf inner;
      inner.leb(DATA_PLAIN_TEXT);
      inner.leb(ins_text.b.size());
      compress_blob.insert(compress_blob.end(), ins_text.b.begin(),
                           ins_text.b.end());
      body.chunk(CH_CONTENT_COMPRESSED, inner.b);
    } else {
      Buf inner;
      inner.leb(DATA_PLAIN_TEXT);
      inner.raw(ins_text.b.data(), ins_text.b.size());
      body.chunk(13 /* CH_CONTENT */, inner.b);
    }
    Buf runs;
    for (auto& r : ins_runs) runs.leb(mix((u64)r.first, r.second));
    body.chunk(CH_CONTENT_KNOWN, runs.b);
    patches.chunk(CH_PATCH_CONTENT, body.b);
  }

  // start branch BEFORE fileinfo: mapping the from version's agents may
  // append to names_buf, which fileinfo's CH_AGENTNAMES bakes below —
  // same build order as the Python writer (walk-first-use numbering,
  // then any from-only agents). Patch encodes carry no start-branch
  // content (ENCODE_PATCH).
  Buf start_branch;
  if (!from_version.empty()) {
    Buf vbuf;
    for (size_t i = 0; i < from_version.size(); i++) {
      bool has_more = i + 1 < from_version.size();
      i64 agent, seq;
      aa.local_to_agent(from_version[i], agent, seq);
      vbuf.leb(mix((u64)map_agent(agent), has_more));
      vbuf.leb((u64)seq);
    }
    start_branch.chunk(CH_VERSION, vbuf.b);
  }

  Buf fileinfo;
  if (docid_len >= 0) {
    Buf d;
    d.leb(DATA_PLAIN_TEXT);
    d.raw(docid, (size_t)docid_len);
    fileinfo.chunk(CH_DOCID, d.b);
  }
  fileinfo.chunk(CH_AGENTNAMES, names_buf.b);
  if (ud_len >= 0) {
    Buf d;
    d.raw(userdata, (size_t)ud_len);
    fileinfo.chunk(CH_USERDATA, d.b);
  }

  Buf result;
  const char magic[] = "DMNDTYPS";
  result.raw((const u8*)magic, 8);
  result.leb(0);  // PROTOCOL_VERSION
  if (have_compressed_chunk) {
    Buf comp;
    comp.leb(compress_blob.size());
    std::vector<u8> lz(compress_blob.size() + compress_blob.size() / 8 + 64);
    i64 ln = dt_lz4_compress(compress_blob.data(), (i64)compress_blob.size(),
                             lz.data(), (i64)lz.size());
    if (ln < 0) return -1;
    comp.raw(lz.data(), (size_t)ln);
    result.chunk(CH_COMPRESSED, comp.b);
  }
  result.chunk(CH_FILEINFO, fileinfo.b);
  result.chunk(CH_STARTBRANCH, start_branch.b);
  patches.chunk(CH_OP_VERSIONS, agent_chunk.b);
  patches.chunk(CH_OP_TYPE_POS, ops_chunk.b);
  patches.chunk(CH_OP_PARENTS, txns_chunk.b);
  result.chunk(CH_PATCHES, patches.b);

  u32 crc = (u32)dt_crc32c(result.b.data(), (i64)result.b.size(), 0);
  Buf crcb;
  crcb.b.assign({(u8)(crc & 0xFF), (u8)((crc >> 8) & 0xFF),
                 (u8)((crc >> 16) & 0xFF), (u8)((crc >> 24) & 0xFF)});
  result.chunk(CH_CRC, crcb.b);

  c->enc_buf = std::move(result.b);
  return (i64)c->enc_buf.size();
}

// ---------------------------------------------------------------- C ABI

extern "C" {

void* dt_ctx_new() { return new Ctx(); }
void dt_ctx_free(void* p) { delete (Ctx*)p; }

void dt_add_agent(void* p, const char* name) {
  Ctx* c = (Ctx*)p;
  c->aa.names.emplace_back(name);
  c->aa.client_runs.emplace_back();
}

// bulk loads (columnar)
void dt_load_graph(void* p, i64 n, const i64* starts, const i64* ends,
                   const i64* shadows, const i64* pindptr, const i64* pflat) {
  Ctx* c = (Ctx*)p;
  c->g.starts.assign(starts, starts + n);
  c->g.ends.assign(ends, ends + n);
  c->g.shadows.assign(shadows, shadows + n);
  c->g.pindptr.assign(pindptr, pindptr + n + 1);
  c->g.pflat.assign(pflat, pflat + pindptr[n]);
  c->g.build_idx();
}

void dt_load_agent_runs(void* p, i64 n, const i64* lv0, const i64* lv1,
                        const i64* agent, const i64* seq0) {
  Ctx* c = (Ctx*)p;
  c->aa.global_runs.clear();
  for (i64 i = 0; i < n; i++) {
    c->aa.global_runs.push_back({lv0[i], lv1[i], agent[i], seq0[i]});
    c->aa.client_runs[agent[i]].push_back(
        {seq0[i], seq0[i] + (lv1[i] - lv0[i]), lv0[i]});
  }
  for (auto& runs : c->aa.client_runs)
    std::sort(runs.begin(), runs.end(),
              [](const AgentRun& a, const AgentRun& b) {
                return a.seq_start < b.seq_start;
              });
  c->aa.build_idx();
}

void dt_load_ops(void* p, i64 n, const i64* lv, const u8* kind,
                 const u8* fwd, const i64* start, const i64* end,
                 const i64* cp) {
  Ctx* c = (Ctx*)p;
  c->ops.runs.clear();
  c->ops.runs.reserve(n);
  for (i64 i = 0; i < n; i++)
    c->ops.runs.push_back({lv[i], kind[i], fwd[i], start[i], end[i], cp[i]});
  c->ops.build_idx();
}

void dt_load_ins_arena(void* p, i64 n, const int32_t* chars) {
  Ctx* c = (Ctx*)p;
  c->ins_arena.assign(chars, chars + n);
}

// transform: fills internal out buffer; returns count
i64 dt_transform(void* p, const i64* from, i64 nf, const i64* merge, i64 nm) {
  Ctx* c = (Ctx*)p;
  transform(c, std::vector<i64>(from, from + nf),
            std::vector<i64>(merge, merge + nm));
  return (i64)c->out.size();
}

// Full native merge: transform + materialize into the ctx's doc buffer.
// init (may be null/0) seeds the document. Returns final doc length.
i64 dt_merge_into_doc(void* p, const int32_t* init, i64 init_len,
                      const i64* from, i64 nf, const i64* merge, i64 nm) {
  Ctx* c = (Ctx*)p;
  c->doc = TextBuf();
  if (init_len > 0) c->doc.insert(0, init, init_len);
  c->merge_no_ff = (nf == 0 && init_len == 0);
  transform(c, std::vector<i64>(from, from + nf),
            std::vector<i64>(merge, merge + nm));
  c->merge_no_ff = false;
  PROF(doc);
  size_t rope_until = c->out.size();
  bool assemble = c->zone_ff_base && c->last_tracker != nullptr;
  if (assemble) rope_until = c->ff_split;
#ifdef DT_PROF
  i64 ff_lvs = 0;
  for (size_t oi = 0; oi < c->ff_split; oi++) ff_lvs += c->out[oi].len;
  fprintf(stderr,
          "merge_into_doc: assemble=%d rope_rows=%zu ff_split=%zu "
          "ff_lvs=%lld\n",
          (int)assemble, rope_until, (size_t)c->ff_split, (long long)ff_lvs);
#endif
  for (size_t oi = 0; oi < rope_until; oi++) {
    const XfOp& x = c->out[oi];
    if (x.pos < 0) continue;
    if (x.kind == INS) {
      // content chars for [lv, lv+len): arena offset via the op run's cp
      const OpRun& run = c->ops.runs[c->ops.find_idx(x.lv)];
      i64 cp = run.cp + (x.lv - run.lv);
      c->doc.insert(x.pos, c->ins_arena.data() + cp, x.len);
    } else {
      c->doc.erase(x.pos, x.len);
    }
  }
  if (assemble) {
    // Zone portion assembled STRAIGHT FROM THE TRACKER in one in-order
    // pass instead of per-op rope surgery: the content tree is already
    // in merged-document order, and an item is visible at the merged
    // version iff it was never deleted (everything in a forward merge's
    // zone is included in the merge frontier, so upstream-visibility
    // degenerates to !ever — same rule the device linearizer uses,
    // diamond_types_tpu/tpu/linearize.py). Underwater ids tile the rope
    // state after FF (zone_ff_base above); real ids pull arena content.
    std::vector<int32_t> base((size_t)c->doc.total);
    c->doc.dump(base.data());
    // two passes: exact-size the buffer, then raw copies (entries are
    // tiny on fragmented histories; per-entry vector bookkeeping costs
    // as much as the copy itself)
    i64 total = 0;
    for (BLeaf* lf = c->last_tracker->first_leaf; lf; lf = lf->next)
      for (int i = 0; i < lf->n; i++) {
        const BEntry& e = lf->e[i];
        if (e.ever) continue;
        if (e.ids >= UNDERWATER) {
          i64 p0 = e.ids - UNDERWATER;
          if (p0 >= (i64)base.size()) continue;   // placeholder tail
          total += std::min(e.len, (i64)base.size() - p0);
        } else {
          total += e.len;
        }
      }
    std::vector<int32_t> fin((size_t)total);
    int32_t* dst = fin.data();
    for (BLeaf* lf = c->last_tracker->first_leaf; lf; lf = lf->next)
      for (int i = 0; i < lf->n; i++) {
        const BEntry& e = lf->e[i];
        if (e.ever) continue;
        if (e.ids >= UNDERWATER) {
          i64 p0 = e.ids - UNDERWATER;
          if (p0 >= (i64)base.size()) continue;   // placeholder tail
          i64 n = std::min(e.len, (i64)base.size() - p0);
          std::memcpy(dst, base.data() + p0, (size_t)n * 4);
          dst += n;
        } else {
          const OpRun& run = c->ops.runs[c->ops.find_idx(e.ids)];
          i64 cp = run.cp + (e.ids - run.lv);
          std::memcpy(dst, c->ins_arena.data() + cp, (size_t)e.len * 4);
          dst += e.len;
        }
      }
    c->doc = TextBuf();
    if (!fin.empty()) c->doc.insert(0, fin.data(), (i64)fin.size());
  }
  // plain merges don't need the tracker afterwards — release its O(zone)
  // tables instead of pinning them on the context (dt_transform callers
  // that want dt_dump_tracker keep theirs); zone_common is cleared with it
  // so the dump/zone_common pair can never disagree about which transform
  // they describe
  c->last_tracker.reset();
  c->zone_common.clear();
  return c->doc.total;
}

void dt_get_doc(void* p, int32_t* out) { ((Ctx*)p)->doc.dump(out); }

void dt_get_out(void* p, i64* lv, i64* len, u8* kind, u8* fwd, i64* pos) {
  Ctx* c = (Ctx*)p;
  for (size_t i = 0; i < c->out.size(); i++) {
    lv[i] = c->out[i].lv;
    len[i] = c->out[i].len;
    kind[i] = c->out[i].kind;
    fwd[i] = c->out[i].fwd;
    pos[i] = c->out[i].pos;
  }
}

// Tracker item-table export (validation ground truth for the device
// linearizer, diamond_types_tpu/tpu/linearize.py): after dt_transform the
// last tracker is dumped in DOCUMENT ORDER as per-entry rows
// (ids, len, origin_left, origin_right, state, ever). Returns row count
// (call with null buffers to size). Rows include the underwater sentinel
// span(s); callers filter ids >= 1<<62.
i64 dt_dump_tracker(void* p, i64 cap, i64* ids, i64* len, i64* ol,
                    i64* orr, i64* state, u8* ever) {
  Ctx* c = (Ctx*)p;
  if (!c->last_tracker) return 0;
  i64 k = 0;
  for (BLeaf* lf = c->last_tracker->first_leaf; lf; lf = lf->next)
    for (int i = 0; i < lf->n; i++, k++)
      if (k < cap) {
        ids[k] = lf->e[i].ids;
        len[k] = lf->e[i].len;
        ol[k] = lf->e[i].ol;
        orr[k] = lf->e[i].orr;
        state[k] = lf->e[i].state;
        ever[k] = lf->e[i].ever ? 1 : 0;
      }
  return k;
}

// Delete-target table export: the last tracker's op-LV -> deleted-items
// map (lv0, lv1, t0, t1, fwd rows; op lv0+k targets item t0+k when fwd,
// t1-1-k when reversed). Recorded in apply order — callers sort by lv0.
// Same two-call sizing protocol as dt_dump_tracker. A delete op's target
// set is intrinsic to the op (fixed by its position + parent version),
// so these rows are valid for ANY schedule over the same conflict zone —
// the fork/join plan executor builds its write journal from them
// (diamond_types_tpu/tpu/plan_kernels.py).
i64 dt_dump_del_rows(void* p, i64 cap, i64* lv0, i64* lv1, i64* t0,
                     i64* t1, u8* fwd) {
  Ctx* c = (Ctx*)p;
  if (!c->last_tracker) return 0;
  const auto& dl = c->last_tracker->del_list;
  i64 k = 0;
  for (const DelRow& r : dl) {
    if (k < cap) {
      lv0[k] = r.lv0;
      lv1[k] = r.lv1;
      t0[k] = r.t0;
      t1[k] = r.t1;
      fwd[k] = r.fwd ? 1 : 0;
    }
    k++;
  }
  return k;
}

// Release the retained tracker + zone frontier (callers that are done
// with dt_dump_tracker / dt_get_zone_common free the O(zone) tables).
void dt_release_tracker(void* p) {
  Ctx* c = (Ctx*)p;
  c->last_tracker.reset();
  c->zone_common.clear();
}

// Common-ancestor frontier of the last transform's conflict zone.
i64 dt_get_zone_common(void* p, i64* buf, i64 cap) {
  Ctx* c = (Ctx*)p;
  i64 n = std::min((i64)c->zone_common.size(), cap);
  for (i64 i = 0; i < n; i++) buf[i] = c->zone_common[i];
  return (i64)c->zone_common.size();
}

i64 dt_get_out_frontier(void* p, i64* buf, i64 cap) {
  Ctx* c = (Ctx*)p;
  i64 n = std::min((i64)c->out_frontier.size(), cap);
  for (i64 i = 0; i < n; i++) buf[i] = c->out_frontier[i];
  return (i64)c->out_frontier.size();
}

// Structured merge-kernel event counters (process-global; order matches
// native/core.py EVENT_COUNTER_NAMES). Returns the counter count.
i64 dt_get_counters(unsigned long long* out, i64 cap) {
  const unsigned long long vals[] = {
      g_events.integrate_calls, g_events.integrate_scan_iters,
      g_events.apply_ins_runs, g_events.apply_del_runs,
      g_events.advance_calls, g_events.retreat_calls,
      g_events.walk_steps, g_events.diff_calls};
  i64 k = (i64)(sizeof(vals) / sizeof(vals[0]));
  for (i64 i = 0; i < std::min(cap, k); i++) out[i] = vals[i];
  return k;
}

void dt_reset_counters() { g_events = EventCounters{}; }

// Colliding concurrent inserts during the last dt_transform on this ctx
// (reference: has_conflicts_when_merging, src/list/merge.rs:51).
i64 dt_last_collisions(void* p) { return ((Ctx*)p)->last_collisions; }

// ---- zone-engine composer (host prep; see Composer above) ----
//
// Protocol: dt_compose_plan composes every entry span and caches the
// results in the ctx; dt_compose_counts reports per-entry sizes (5 i64
// each: nq, nch, nblk, ndel_base, ndel_own); dt_compose_fetch fills the
// caller's flat arrays (entry-concatenated, entry-local indices) and
// frees the cache. Returns -1 on unsupported input (reverse insert
// runs / out-of-range positions) — caller falls back to Python.
i64 dt_compose_plan(void* p, i64 n, const i64* s0, const i64* s1) {
  Ctx* c = (Ctx*)p;
  c->compose_serial++;
  c->composed.clear();
  c->composed.resize((size_t)n);
  for (i64 k = 0; k < n; k++) {
    Composer comp(true);
    if (!compose_span_ops(c, comp, {s0[k], s1[k]})) {
      c->composed.clear();
      return -1;
    }
    comp.finish(c->composed[k]);
  }
  return 0;
}

i64 dt_compose_serial(void* p) { return ((Ctx*)p)->compose_serial; }

void dt_compose_counts(void* p, i64* out) {
  Ctx* c = (Ctx*)p;
  for (size_t k = 0; k < c->composed.size(); k++) {
    const ComposedOut& o = c->composed[k];
    out[k * 5 + 0] = (i64)o.q_cursor.size();
    out[k * 5 + 1] = (i64)o.ch_lv.size();
    out[k * 5 + 2] = (i64)o.blk_start.size();
    out[k * 5 + 3] = (i64)o.db0.size();
    out[k * 5 + 4] = (i64)o.do0.size();
  }
}

void dt_compose_fetch(void* p, i64* q, i64* ch_lv, int32_t* ch_block,
                      u8* ch_head, u8* ch_kind, i64* ch_anchor,
                      int32_t* ch_q, i64* ch_headlv, i64* ch_orrown,
                      int32_t* blk_root_q, i64* blk_root_lv,
                      int32_t* blk_start, int32_t* blk_len,
                      i64* db0, i64* db1, i64* do0, i64* do1) {
  Ctx* c = (Ctx*)p;
  size_t iq = 0, ic = 0, ib = 0, idb = 0, ido = 0;
  for (const ComposedOut& o : c->composed) {
    std::copy(o.q_cursor.begin(), o.q_cursor.end(), q + iq);
    iq += o.q_cursor.size();
    std::copy(o.ch_lv.begin(), o.ch_lv.end(), ch_lv + ic);
    std::copy(o.ch_block.begin(), o.ch_block.end(), ch_block + ic);
    std::copy(o.ch_head.begin(), o.ch_head.end(), ch_head + ic);
    std::copy(o.ch_kind.begin(), o.ch_kind.end(), ch_kind + ic);
    std::copy(o.ch_anchor.begin(), o.ch_anchor.end(), ch_anchor + ic);
    std::copy(o.ch_q.begin(), o.ch_q.end(), ch_q + ic);
    std::copy(o.ch_headlv.begin(), o.ch_headlv.end(), ch_headlv + ic);
    std::copy(o.ch_orrown.begin(), o.ch_orrown.end(), ch_orrown + ic);
    ic += o.ch_lv.size();
    std::copy(o.blk_root_q.begin(), o.blk_root_q.end(), blk_root_q + ib);
    std::copy(o.blk_root_lv.begin(), o.blk_root_lv.end(), blk_root_lv + ib);
    std::copy(o.blk_start.begin(), o.blk_start.end(), blk_start + ib);
    std::copy(o.blk_len.begin(), o.blk_len.end(), blk_len + ib);
    ib += o.blk_start.size();
    std::copy(o.db0.begin(), o.db0.end(), db0 + idb);
    std::copy(o.db1.begin(), o.db1.end(), db1 + idb);
    idb += o.db0.size();
    std::copy(o.do0.begin(), o.do0.end(), do0 + ido);
    std::copy(o.do1.begin(), o.do1.end(), do1 + ido);
    ido += o.do0.size();
  }
  c->composed.clear();
  c->composed.shrink_to_fit();
}

// ---------------------------------------------------------------- zone pack
//
// Native zone tape packer (VERDICT r4 #6 — the ~280 ms pure-Python
// pack was the zone engine's remaining host-prep cost): flattens a
// prepared zone (plan actions + composed entries) into the micro-step
// tape arrays of diamond_types_tpu/tpu/zone_kernel.py::pack_zone_tape,
// ARRAY-IDENTICAL to the Python packer (pinned by
// tests/test_zone_kernel.py). Composed entries arrive as the
// entry-concatenated flat columns (counts-prefixed, same layout as
// dt_compose_fetch) so the packer serves both the native and the
// Python fallback composer.


// action columns: kind (plan2 BEGIN=0 FORK=1 MAX=2 DROP=3 APPLY=4),
// a, b per plan.actions semantics. Composed flat columns per the
// counts[5*n] layout. slot map: ins_lv0/ins_cum sorted run table.
// Returns total step count; the caller fetches with dt_zone_pack_fetch
// on the same ctx (the step buffer lives on the ctx — single-threaded
// per ctx, like every other two-call protocol in this file).
// use_cache: read composed entries straight from the ctx's compose
// cache (populated by the immediately-preceding dt_compose_plan) and
// ignore the flat column pointers (they may be null).
extern "C" i64 dt_zone_pack(
    void* p, i64 n_actions, const i64* act_kind, const i64* act_a, const i64* act_b,
    i64 n_entries, const i64* counts, const i64* flat_q, const i64* ch_lv,
    const u8* ch_kind, const i64* ch_anchor, const int32_t* ch_q,
    const i64* ch_orrown, const int32_t* blk_root_q, const i64* blk_root_lv,
    const int32_t* blk_start, const int32_t* blk_len, const i64* db0,
    const i64* db1, const i64* do0, const i64* do1, i64 n_runs,
    const i64* ins_lv0, const i64* ins_cum, i64 plen, const i64* agent_k,
    const i64* seq_k, i64 MB, i64 MC, i64 MD, i64 use_cache) {
  // use_cache > 0 is the expected compose serial: both the entry count
  // AND the cache identity must match (two plans can have equal counts)
  Ctx* cx = (Ctx*)p;
  if (use_cache && ((i64)cx->composed.size() != n_entries ||
                    cx->compose_serial != use_cache))
    return -2;  // stale/absent cache: caller re-marshals
  using zonepack::Step;
  const int K_OWN = 1;
  const int OP_BEGIN = 0, OP_FORK = 1, OP_MAX = 2, OP_APPLY = 3;
  const int A_BEGIN = 0, A_FORK = 1, A_MAX = 2, A_DROP = 3, A_APPLY = 4;

  auto slot_of = [&](i64 lv) -> i64 {
    // searchsorted(ins_lv0, lv, 'right') - 1
    const i64* hi = std::upper_bound(ins_lv0, ins_lv0 + n_runs, lv);
    i64 j = (hi - ins_lv0) - 1;
    return plen + ins_cum[j] + (lv - ins_lv0[j]);
  };

  // per-entry offsets into the flat columns (marshalled path only)
  std::vector<i64> off_q, off_ch, off_blk, off_db, off_do;
  if (!use_cache) {
    off_q.assign(n_entries + 1, 0); off_ch.assign(n_entries + 1, 0);
    off_blk.assign(n_entries + 1, 0); off_db.assign(n_entries + 1, 0);
    off_do.assign(n_entries + 1, 0);
    for (i64 k = 0; k < n_entries; k++) {
      off_q[k + 1] = off_q[k] + counts[k * 5 + 0];
      off_ch[k + 1] = off_ch[k] + counts[k * 5 + 1];
      off_blk[k + 1] = off_blk[k] + counts[k * 5 + 2];
      off_db[k + 1] = off_db[k] + counts[k * 5 + 3];
      off_do[k + 1] = off_do[k] + counts[k * 5 + 4];
    }
  }

  // uniform per-entry view over either source
  struct EView {
    const i64* q; i64 nq;
    const i64 *lv, *anchor, *orrown; const u8* kind;
    const int32_t* qidx; i64 nc;
    const int32_t *brq, *bstart, *blen; const i64* brlv; i64 nb;
    const i64 *pdb0, *pdb1; i64 ndb;
    const i64 *pdo0, *pdo1; i64 ndo;
  };
  auto view_of = [&](i64 e) -> EView {
    EView v;
    if (use_cache) {
      const ComposedOut& o = cx->composed[(size_t)e];
      v.q = o.q_cursor.data(); v.nq = (i64)o.q_cursor.size();
      v.lv = o.ch_lv.data(); v.anchor = o.ch_anchor.data();
      v.orrown = o.ch_orrown.data(); v.kind = o.ch_kind.data();
      v.qidx = o.ch_q.data(); v.nc = (i64)o.ch_lv.size();
      v.brq = o.blk_root_q.data(); v.bstart = o.blk_start.data();
      v.blen = o.blk_len.data(); v.brlv = o.blk_root_lv.data();
      v.nb = (i64)o.blk_start.size();
      v.pdb0 = o.db0.data(); v.pdb1 = o.db1.data();
      v.ndb = (i64)o.db0.size();
      v.pdo0 = o.do0.data(); v.pdo1 = o.do1.data();
      v.ndo = (i64)o.do0.size();
    } else {
      v.q = flat_q + off_q[e]; v.nq = counts[e * 5 + 0];
      v.lv = ch_lv + off_ch[e]; v.anchor = ch_anchor + off_ch[e];
      v.orrown = ch_orrown + off_ch[e]; v.kind = ch_kind + off_ch[e];
      v.qidx = ch_q + off_ch[e]; v.nc = counts[e * 5 + 1];
      v.brq = blk_root_q + off_blk[e]; v.bstart = blk_start + off_blk[e];
      v.blen = blk_len + off_blk[e]; v.brlv = blk_root_lv + off_blk[e];
      v.nb = counts[e * 5 + 2];
      v.pdb0 = db0 + off_db[e]; v.pdb1 = db1 + off_db[e];
      v.ndb = counts[e * 5 + 3];
      v.pdo0 = do0 + off_do[e]; v.pdo1 = do1 + off_do[e];
      v.ndo = counts[e * 5 + 4];
    }
    return v;
  };

  zonepack::PackState ps;
  ps.MB = MB; ps.MC = MC; ps.MD = MD;
  ps.steps.reserve((size_t)n_actions * 2);

  for (i64 ai = 0; ai < n_actions; ai++) {
    i64 kind = act_kind[ai];
    if (kind == A_BEGIN) {
      ps.new_step(OP_BEGIN, (int32_t)act_a[ai], 0, 0);
    } else if (kind == A_FORK) {
      ps.new_step(OP_FORK, (int32_t)act_a[ai], (int32_t)act_b[ai], 0);
    } else if (kind == A_MAX) {
      // tape a = src, b = dst (zone_kernel.py:257)
      ps.new_step(OP_MAX, (int32_t)act_b[ai], (int32_t)act_a[ai], 0);
    } else if (kind == A_DROP) {
      continue;
    } else if (kind == A_APPLY) {
      i64 e = act_a[ai];
      int32_t row = (int32_t)act_b[ai];
      Step* cur = ps.new_step(OP_APPLY, row, 0, 1);
      auto next_sub = [&]() { return ps.new_step(OP_APPLY, row, 0, 0); };

      const EView v = view_of(e);
      auto q_at = [&](i64 qi) -> i64 {
        // Python: flat_q[clip(ch_q, 0, None)] with a zeros(1) fallback
        // when the entry has no queries
        if (v.nq == 0) return 0;
        return v.q[qi >= 0 ? qi : 0];
      };
      auto char_cols = [&](i64 pos, int32_t* out7, int32_t blk) {
        i64 slot = slot_of(v.lv[pos]);
        int kd = v.kind[pos];
        i64 anchor = v.anchor[pos] >= 0 ? slot_of(v.anchor[pos]) : -1;
        i64 orr = v.orrown[pos] >= 0 ? slot_of(v.orrown[pos]) : -1;
        i64 c_of = q_at(v.qidx[pos]);
        i64 ol_static, ol_coord;
        if (kd == 0) ol_static = slot - 1;
        else if (kd == K_OWN) ol_static = anchor;
        else ol_static = (c_of == 0) ? -1 : -2;
        ol_coord = (kd >= 2 && c_of > 0) ? c_of : 0;
        out7[0] = (int32_t)slot;
        out7[1] = (int32_t)ol_static;
        out7[2] = (int32_t)ol_coord;
        out7[3] = (int32_t)orr;
        out7[4] = blk;
        out7[5] = (int32_t)agent_k[slot];
        out7[6] = (int32_t)seq_k[slot];
      };

      if (v.nc) {
        for (i64 b = 0; b < v.nb; b++) {
          i64 lo = v.bstart[b];
          i64 hi = lo + v.blen[b];
          bool first = true;
          i64 pos = lo;
          while (pos < hi) {
            if ((i64)cur->blocks.size() >= MB ||
                (i64)cur->chars.size() >= MC)
              cur = next_sub();
            i64 take = std::min(hi - pos, MC - (i64)cur->chars.size());
            int32_t cursor = first ? (int32_t)v.q[v.brq[b]] : -2;
            int32_t prev = first ? -1 : (int32_t)slot_of(v.lv[pos - 1]);
            cur->blocks.push_back(std::array<int32_t, 5>{{
                cursor, prev, (int32_t)slot_of(v.brlv[b]),
                (int32_t)cur->chars.size(), (int32_t)take}});
            int32_t blk = (int32_t)cur->blocks.size() - 1;
            for (i64 k = 0; k < take; k++) {
              std::array<int32_t, 7> row7;
              char_cols(pos + k, row7.data(), blk);
              cur->chars.push_back(row7);
            }
            pos += take;
            first = false;
          }
        }
      }
      for (i64 d = 0; d < v.ndb; d++) {
        if ((i64)cur->dels.size() >= MD) cur = next_sub();
        cur->dels.push_back(std::array<int32_t, 3>{{
            0, (int32_t)v.pdb0[d], (int32_t)v.pdb1[d]}});
      }
      for (i64 d = 0; d < v.ndo; d++) {
        if ((i64)cur->dels.size() >= MD) cur = next_sub();
        i64 s0 = slot_of(v.pdo0[d]);
        cur->dels.push_back(std::array<int32_t, 3>{{
            1, (int32_t)s0, (int32_t)(s0 + (v.pdo1[d] - v.pdo0[d]))}});
      }
    } else {
      return -1;  // unknown action kind
    }
  }
  if (use_cache) {
    // consumed: a long-lived ctx must not pin O(document) composed
    // columns after the pack (the fetch path clears its own copy)
    cx->composed.clear();
    cx->composed.shrink_to_fit();
  }
  cx->pack_steps = std::move(ps.steps);
  return (i64)cx->pack_steps.size();
}

// Fill the caller's [T]-and-[T,M]-shaped arrays INCLUDING the pad
// cells (the caller allocates with np.empty — zero/pad-initializing
// ~100 MB of tape in numpy costs more than writing it once here) and
// free the buffer. Pads: blk_cursor/blk_prev/ch_slot/ch_ol_static/
// del_kind -1, everything else 0.
extern "C" void dt_zone_pack_fetch(
    void* p, int32_t* op, int32_t* arg_a, int32_t* arg_b, int32_t* snap_flag,
    int32_t* blk_cursor, int32_t* blk_prev, int32_t* blk_root,
    int32_t* blk_start_o, int32_t* blk_len_o, int32_t* ch_slot,
    int32_t* ch_ol_static, int32_t* ch_ol_coord, int32_t* ch_orr_own,
    int32_t* ch_blk, int32_t* ch_agent, int32_t* ch_seq, int32_t* del_kind,
    int32_t* del_a, int32_t* del_b, i64 MB, i64 MC, i64 MD) {
  Ctx* c = (Ctx*)p;
  i64 T = (i64)c->pack_steps.size();
  i64 Tp = T > 0 ? T : 1;
  std::memset(op, 0, (size_t)Tp * 4);
  std::memset(arg_a, 0, (size_t)Tp * 4);
  std::memset(arg_b, 0, (size_t)Tp * 4);
  std::memset(snap_flag, 0, (size_t)Tp * 4);
  std::memset(blk_cursor, 0xFF, (size_t)(Tp * MB) * 4);   // -1
  std::memset(blk_prev, 0xFF, (size_t)(Tp * MB) * 4);     // -1
  std::memset(blk_root, 0, (size_t)(Tp * MB) * 4);
  std::memset(blk_start_o, 0, (size_t)(Tp * MB) * 4);
  std::memset(blk_len_o, 0, (size_t)(Tp * MB) * 4);
  std::memset(ch_slot, 0xFF, (size_t)(Tp * MC) * 4);      // -1
  std::memset(ch_ol_static, 0xFF, (size_t)(Tp * MC) * 4); // -1
  std::memset(ch_ol_coord, 0, (size_t)(Tp * MC) * 4);
  std::memset(ch_orr_own, 0xFF, (size_t)(Tp * MC) * 4);   // -1
  std::memset(ch_blk, 0, (size_t)(Tp * MC) * 4);
  std::memset(ch_agent, 0, (size_t)(Tp * MC) * 4);
  std::memset(ch_seq, 0, (size_t)(Tp * MC) * 4);
  std::memset(del_kind, 0xFF, (size_t)(Tp * MD) * 4);     // -1
  std::memset(del_a, 0, (size_t)(Tp * MD) * 4);
  std::memset(del_b, 0, (size_t)(Tp * MD) * 4);
  for (size_t t = 0; t < c->pack_steps.size(); t++) {
    const zonepack::Step& s = c->pack_steps[t];
    op[t] = s.op; arg_a[t] = s.a; arg_b[t] = s.b; snap_flag[t] = s.snap;
    for (size_t i = 0; i < s.blocks.size(); i++) {
      blk_cursor[t * MB + i] = s.blocks[i][0];
      blk_prev[t * MB + i] = s.blocks[i][1];
      blk_root[t * MB + i] = s.blocks[i][2];
      blk_start_o[t * MB + i] = s.blocks[i][3];
      blk_len_o[t * MB + i] = s.blocks[i][4];
    }
    for (size_t i = 0; i < s.chars.size(); i++) {
      ch_slot[t * MC + i] = s.chars[i][0];
      ch_ol_static[t * MC + i] = s.chars[i][1];
      ch_ol_coord[t * MC + i] = s.chars[i][2];
      ch_orr_own[t * MC + i] = s.chars[i][3];
      ch_blk[t * MC + i] = s.chars[i][4];
      ch_agent[t * MC + i] = s.chars[i][5];
      ch_seq[t * MC + i] = s.chars[i][6];
    }
    for (size_t i = 0; i < s.dels.size(); i++) {
      del_kind[t * MD + i] = s.dels[i][0];
      del_a[t * MD + i] = s.dels[i][1];
      del_b[t * MD + i] = s.dels[i][2];
    }
  }
  c->pack_steps.clear();
  c->pack_steps.shrink_to_fit();
}

// Graph rebuild from decoded rows (decode.py _rebuild_from_native's hot
// loop): RLE-merge linear rows, compute shadows, sort parents, and emit
// the version frontier — the exact incremental semantics of
// causalgraph/graph.py::push + _advance_known_run, batch-applied.
// Outputs (caller-allocated at n / len(par) upper bounds): merged
// starts/ends/shadows, parent CSR (pindptr[m+1], pflat), child CSR
// (cindptr[m+1], cflat, croot with its count in croot_n[0]), version
// (ascending; count in ver_n[0]). Returns the merged run count m.
extern "C" i64 dt_graph_rebuild(i64 n, const i64* start, const i64* end,
                                const i64* off, const i64* par,
                                i64* m_starts, i64* m_ends, i64* m_shadows,
                                i64* m_pindptr, i64* m_pflat,
                                i64* m_cindptr, i64* m_cflat, i64* m_croot,
                                i64* croot_n, i64* ver_out, i64* ver_n) {
  i64 m = 0;
  i64 pk = 0;
  m_pindptr[0] = 0;
  std::vector<i64> psort;
  auto find_idx = [&](i64 v) -> i64 {
    // binary search over the merged runs built so far
    i64 lo = 0, hi = m;
    while (lo < hi) {
      i64 mid = (lo + hi) / 2;
      if (v < m_starts[mid]) hi = mid;
      else if (v >= m_ends[mid]) lo = mid + 1;
      else return mid;
    }
    return -1;
  };
  for (i64 i = 0; i < n; i++) {
    i64 np = off[i + 1] - off[i];
    const i64* ps = par + off[i];
    // parents must reference EARLIER LVs: the per-row Python path
    // rejects forward references loudly (find_idx KeyError), and a
    // batch path that resolved them after the fact would install a
    // silently-corrupt graph
    for (i64 k = 0; k < np; k++)
      if (ps[k] >= start[i]) return -1;
    // RLE extend: linear continuation of the previous run
    if (m > 0 && np == 1 && ps[0] == m_ends[m - 1] - 1 &&
        m_ends[m - 1] == start[i]) {
      m_ends[m - 1] = end[i];
      continue;
    }
    // shadow walk (graph.py push)
    i64 shadow = start[i];
    bool moved = true;
    while (moved && shadow >= 1) {
      moved = false;
      for (i64 k = 0; k < np; k++) {
        if (ps[k] == shadow - 1) {
          i64 j = find_idx(shadow - 1);
          if (j < 0) return -1;  // corrupt rows: caller falls back
          shadow = m_shadows[j];
          moved = true;
          break;
        }
      }
    }
    m_starts[m] = start[i];
    m_ends[m] = end[i];
    m_shadows[m] = shadow;
    psort.assign(ps, ps + np);
    std::sort(psort.begin(), psort.end());
    for (i64 v : psort) m_pflat[pk++] = v;
    m_pindptr[m + 1] = pk;
    m++;
  }
  // child CSR + roots
  std::fill(m_cindptr, m_cindptr + m + 1, 0);
  i64 nroot = 0;
  for (i64 i = 0; i < m; i++) {
    i64 np = m_pindptr[i + 1] - m_pindptr[i];
    if (np == 0) m_croot[nroot++] = i;
    for (i64 k = m_pindptr[i]; k < m_pindptr[i + 1]; k++) {
      i64 j = find_idx(m_pflat[k]);
      if (j < 0) return -1;  // corrupt rows: caller falls back
      m_cindptr[j + 1]++;
    }
  }
  croot_n[0] = nroot;
  for (i64 i = 0; i < m; i++) m_cindptr[i + 1] += m_cindptr[i];
  {
    std::vector<i64> fill(m_cindptr, m_cindptr + m);
    for (i64 i = 0; i < m; i++)
      for (i64 k = m_pindptr[i]; k < m_pindptr[i + 1]; k++)
        m_cflat[fill[(size_t)find_idx(m_pflat[k])]++] = i;
  }
  // version frontier: entry-final LVs never referenced as a parent
  {
    std::vector<i64> allp(m_pflat, m_pflat + pk);
    std::sort(allp.begin(), allp.end());
    i64 kv = 0;
    for (i64 i = 0; i < m; i++) {
      i64 last = m_ends[i] - 1;
      if (!std::binary_search(allp.begin(), allp.end(), last))
        ver_out[kv++] = last;
    }
    ver_n[0] = kv;
  }
  return m;
}

// Zone insert-run collection (prepare_zone's table pass — ~50k
// Python piece iterations on node_nodecc): INS sub-runs of the given
// (disjoint, ascending) spans as (lv0, len, arena cp) columns. Returns
// the run count, or -1 when an insert lacks stored content. The caller
// sizes the outputs at #op_runs + #spans (a span boundary can split a
// run, adding at most one piece per span edge).
extern "C" i64 dt_zone_ins_runs(void* p, i64 nspans, const i64* s0,
                                const i64* s1, i64* lv0, i64* len_out,
                                i64* cp_out) {
  Ctx* c = (Ctx*)p;
  i64 k = 0;
  for (i64 i = 0; i < nspans; i++) {
    i64 lo = s0[i], hi = s1[i];
    if (hi <= lo) continue;
    size_t oi = c->ops.find_idx(lo);
    i64 pos = lo;
    while (pos < hi) {
      const OpRun& run = c->ops.runs[oi];
      i64 run_end = run.lv + (run.end - run.start);
      i64 o0 = pos - run.lv;
      i64 o1 = std::min(hi, run_end) - run.lv;
      if (run.kind == INS) {
        if (run.cp < 0) return -1;  // zone insert without stored content
        lv0[k] = run.lv + o0;
        len_out[k] = o1 - o0;
        cp_out[k] = run.cp + o0;
        k++;
      }
      pos = run.lv + o1;
      oi++;
    }
  }
  return k;
}

// Linear fast-forward prefix composition (assemble_prefix's hot loop):
// compose the (sorted, causally linear) spans over an EMPTY base and
// return the alive own pieces in document order — the caller joins their
// arena content. Returns piece count, or -1 on unsupported input.
i64 dt_compose_linear(void* p, i64 nspans, const i64* s0, const i64* s1) {
  Ctx* c = (Ctx*)p;
  Composer comp(false);
  for (i64 k = 0; k < nspans; k++)
    if (!compose_span_ops(c, comp, {s0[k], s1[k]})) return -1;
  c->linear_pieces.clear();
  std::vector<int> st;
  int cur = comp.root;
  while (!st.empty() || cur >= 0) {
    while (cur >= 0) { st.push_back(cur); cur = comp.A[cur].l; }
    cur = st.back();
    st.pop_back();
    const CompPiece& pc = comp.A[cur];
    if (pc.base < 0 && pc.alive)
      c->linear_pieces.emplace_back(pc.lv, pc.length);
    cur = pc.r;
  }
  return (i64)c->linear_pieces.size();
}

void dt_fetch_linear(void* p, i64* lv, i64* len) {
  Ctx* c = (Ctx*)p;
  for (size_t i = 0; i < c->linear_pieces.size(); i++) {
    lv[i] = c->linear_pieces[i].first;
    len[i] = c->linear_pieces[i].second;
  }
  c->linear_pieces.clear();
  c->linear_pieces.shrink_to_fit();
}

// Native full-snapshot v1 encode (see encode_full_impl above). docid_len /
// ud_len of -1 mean "absent". Returns the encoded size (fetch with
// dt_encode_fetch) or -1 on failure (caller falls back to Python).
i64 dt_encode_full(void* p, const u8* docid, i64 docid_len,
                   const u8* userdata, i64 ud_len, i64 store_ins,
                   i64 compress) {
  return encode_impl((Ctx*)p, docid, docid_len, userdata, ud_len,
                     store_ins != 0, compress != 0, {});
}

// Patch encode (reference: encode_oplog.rs encode_from): ops above
// `from` only, start branch = `from` as agent versions, no start-branch
// content. Returns -2 when `from` is not an ancestor of the oplog tip.
i64 dt_encode_patch(void* p, const u8* docid, i64 docid_len,
                    const u8* userdata, i64 ud_len, i64 store_ins,
                    i64 compress, const i64* from, i64 nf) {
  return encode_impl((Ctx*)p, docid, docid_len, userdata, ud_len,
                     store_ins != 0, compress != 0,
                     std::vector<i64>(from, from + nf));
}

void dt_encode_fetch(void* p, u8* out) {
  Ctx* c = (Ctx*)p;
  std::memcpy(out, c->enc_buf.data(), c->enc_buf.size());
  c->enc_buf.clear();
  c->enc_buf.shrink_to_fit();
}

}  // extern "C"
