// dt_core — native host core for diamond_types_tpu.
//
// Implements the merge-critical host path in C++ (the reference implements
// this tier in Rust; see SURVEY.md §2 native-component note):
//   * columnar causal graph + DAG queries (diff / find_conflicting)
//     (reference: src/causalgraph/graph/tools.rs)
//   * frontier movement (reference: src/frontier.rs)
//   * spanning-tree conflict walker (reference: src/listmerge/txn_trace.rs)
//   * treap-based merge tracker with dual current/upstream aggregates and
//     YjsMod integrate (reference: src/listmerge/merge.rs, yjsspan.rs,
//     advance_retreat.rs — same design as the Python tracker in
//     diamond_types_tpu/listmerge/tracker.py)
//   * the transformed-op pipeline incl. fast-forward mode
//     (reference: src/listmerge/merge.rs:585-941)
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
// Content (text) stays on the Python side; this library deals purely in
// LV spans and positions.

#include <algorithm>
#include <cassert>
#include <cstdint>
#include <cstring>
#include <map>
#include <queue>
#include <string>
#include <vector>

typedef int64_t i64;
typedef uint8_t u8;

static const i64 ROOT = -1;
static const i64 UNDERWATER = 1ll << 62;

// ---------------------------------------------------------------- utilities

struct Span { i64 start, end; };

static inline bool span_empty(const Span& s) { return s.end <= s.start; }

static void push_reversed_rle(std::vector<Span>& out, Span s) {
  if (!out.empty() && s.end == out.back().start) out.back().start = s.start;
  else out.push_back(s);
}

// ---------------------------------------------------------------- graph

struct Graph {
  std::vector<i64> starts, ends, shadows;
  std::vector<std::vector<i64>> parents;

  size_t find_idx(i64 v) const {
    size_t lo = 0, hi = starts.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (starts[mid] <= v) lo = mid + 1; else hi = mid; }
    return lo - 1;
  }

  void parents_at(i64 v, std::vector<i64>& out) const {
    size_t i = find_idx(v);
    out.clear();
    if (v > starts[i]) out.push_back(v - 1);
    else out = parents[i];
  }

  bool entry_contains(size_t idx, i64 v) const {
    return starts[idx] <= v && v < ends[idx];
  }

  bool is_direct_descendant_coarse(i64 a, i64 b) const {
    if (a == b || b == ROOT) return true;
    return a > b && entry_contains(find_idx(a), b);
  }

  bool frontier_contains_version(const std::vector<i64>& f, i64 target) const {
    if (target == ROOT) return true;
    for (i64 o : f) if (o == target) return true;
    if (f.empty()) return false;
    for (i64 o : f) if (o > target && shadows[find_idx(o)] <= target) return true;
    std::priority_queue<i64> q;
    for (i64 o : f) if (o > target) q.push(o);
    while (!q.empty()) {
      i64 order = q.top(); q.pop();
      size_t i = find_idx(order);
      if (shadows[i] <= target) return true;
      i64 start = starts[i];
      while (!q.empty() && q.top() >= start) q.pop();
      for (i64 p : parents[i]) {
        if (p == target) return true;
        if (p > target) q.push(p);
      }
    }
    return false;
  }

  // diff: returns (only_a, only_b) in DESCENDING order.
  enum Flag : u8 { OnlyA = 0, OnlyB = 1, Shared = 2 };

  void diff_rev(const std::vector<i64>& a, const std::vector<i64>& b,
                std::vector<Span>& only_a, std::vector<Span>& only_b) const {
    only_a.clear(); only_b.clear();
    if (a == b) return;
    if (a.size() == 1 && b.size() == 1) {
      if (is_direct_descendant_coarse(a[0], b[0])) {
        if (a[0] != b[0]) only_a.push_back({b[0] + 1, a[0] + 1});
        return;
      }
      if (is_direct_descendant_coarse(b[0], a[0])) {
        only_b.push_back({a[0] + 1, b[0] + 1});
        return;
      }
    }
    diff_slow(a, b, only_a, only_b);
  }

  void diff_slow(const std::vector<i64>& a, const std::vector<i64>& b,
                 std::vector<Span>& only_a, std::vector<Span>& only_b) const {
    // max-heap of (lv, flag)
    std::priority_queue<std::pair<i64, u8>> q;
    for (i64 v : a) q.push({v, OnlyA});
    for (i64 v : b) q.push({v, OnlyB});
    long num_shared = 0;

    auto mark = [&](i64 lo, i64 hi, u8 flag) {
      if (flag == Shared) return;
      push_reversed_rle(flag == OnlyA ? only_a : only_b, {lo, hi + 1});
    };

    while (!q.empty()) {
      auto [ord, flag] = q.top(); q.pop();
      if (flag == Shared) num_shared--;
      while (!q.empty() && q.top().first == ord) {
        u8 pf = q.top().second; q.pop();
        if (pf != flag) flag = Shared;
        if (pf == Shared) num_shared--;
      }
      size_t i = find_idx(ord);
      i64 start = starts[i];
      while (!q.empty() && q.top().first >= start) {
        i64 peek_ord = q.top().first; u8 pf = q.top().second;
        if (pf != flag) {
          mark(peek_ord + 1, ord, flag);
          ord = peek_ord;
          flag = Shared;
        }
        if (pf == Shared) num_shared--;
        q.pop();
      }
      mark(start, ord, flag);
      for (i64 p : parents[i]) {
        q.push({p, flag});
        if (flag == Shared) num_shared++;
      }
      if ((long)q.size() == num_shared) break;
    }
  }

  // find_conflicting; visits spans (descending); returns common ancestor.
  template <class V>
  std::vector<i64> find_conflicting(const std::vector<i64>& a,
                                    const std::vector<i64>& b, V visit) const {
    if (a == b) return a;
    if (a.size() == 1 && b.size() == 1) {
      if (is_direct_descendant_coarse(a[0], b[0])) {
        if (a[0] != b[0]) visit(Span{b[0] + 1, a[0] + 1}, (u8)OnlyA);
        return b[0] == ROOT ? std::vector<i64>{} : std::vector<i64>{b[0]};
      }
      if (is_direct_descendant_coarse(b[0], a[0])) {
        visit(Span{a[0] + 1, b[0] + 1}, (u8)OnlyB);
        return a[0] == ROOT ? std::vector<i64>{} : std::vector<i64>{a[0]};
      }
    }
    return find_conflicting_slow(a, b, visit);
  }

  struct TimePoint {
    i64 last;
    std::vector<i64> merged;  // sorted, excludes last
    bool operator==(const TimePoint& o) const {
      return last == o.last && merged == o.merged;
    }
    // max-heap: highest last first; among equal, FEWER merged first.
    bool operator<(const TimePoint& o) const {
      if (last != o.last) return last < o.last;
      if (merged.size() != o.merged.size()) return merged.size() > o.merged.size();
      return merged < o.merged;
    }
  };

  template <class V>
  std::vector<i64> find_conflicting_slow(const std::vector<i64>& a,
                                         const std::vector<i64>& b,
                                         V visit) const {
    auto tp = [](const std::vector<i64>& f) {
      TimePoint t;
      if (f.empty()) { t.last = ROOT; return t; }
      t.last = f.back();
      t.merged.assign(f.begin(), f.end() - 1);
      return t;
    };
    std::priority_queue<std::pair<TimePoint, u8>> q;
    q.push({tp(a), OnlyA});
    q.push({tp(b), OnlyB});

    while (true) {
      auto [time, flag] = q.top(); q.pop();
      i64 t = time.last;
      if (t == ROOT) return {};
      while (!q.empty() && q.top().first == time) {
        if (q.top().second != flag) flag = Shared;
        q.pop();
      }
      if (q.empty()) {
        std::vector<i64> fr = time.merged;
        fr.push_back(t);
        return fr;
      }
      for (i64 t2 : time.merged) q.push({TimePoint{t2, {}}, flag});
      size_t i = find_idx(t);
      Span rng{starts[i], t + 1};
      while (true) {
        if (!q.empty()) {
          const TimePoint& peek = q.top().first;
          if (peek.last != ROOT && peek.last >= starts[i]) {
            auto [time2, next_flag] = q.top(); q.pop();
            if (time2.last + 1 < rng.end) {
              i64 offset = time2.last + 1 - starts[i];
              Span rem{starts[i] + offset, rng.end};
              rng = {starts[i], starts[i] + offset};
              visit(rem, flag);
            }
            for (i64 t2 : time2.merged) q.push({TimePoint{t2, {}}, next_flag});
            if (next_flag != flag) flag = Shared;
          } else {
            visit(rng, flag);
            q.push({tp(parents[i]), flag});
            break;
          }
        } else {
          return {rng.end - 1};
        }
      }
    }
  }

  // frontier ops (reference: src/frontier.rs)
  void advance_known_run(std::vector<i64>& f, const std::vector<i64>& ps,
                         Span span) const {
    i64 last = span.end - 1;
    if (ps.size() == 1 && f.size() == 1 && ps[0] == f[0]) { f[0] = last; return; }
    if (f == ps) { f.assign(1, last); return; }
    std::vector<i64> out;
    for (i64 o : f)
      if (std::find(ps.begin(), ps.end(), o) == ps.end()) out.push_back(o);
    out.insert(std::upper_bound(out.begin(), out.end(), last), last);
    f = out;
  }

  void advance(std::vector<i64>& f, Span rng) const {
    i64 start = rng.start;
    size_t i = find_idx(start);
    std::vector<i64> ps;
    while (true) {
      i64 e_end = std::min(ends[i], rng.end);
      parents_at(start, ps);
      advance_known_run(f, ps, {start, e_end});
      if (e_end >= rng.end) break;
      start = e_end;
      i++;
    }
  }

  void retreat(std::vector<i64>& f, Span rng) const {
    if (span_empty(rng)) return;
    i64 start = rng.start, end = rng.end;
    size_t i = find_idx(end - 1);
    std::vector<i64> ps;
    while (true) {
      i64 last_order = end - 1;
      i64 t_start = starts[i];
      if (f.size() == 1) {
        if (start > t_start) { f[0] = start - 1; break; }
        f = parents[i];
      } else {
        f.erase(std::remove(f.begin(), f.end(), last_order), f.end());
        parents_at(std::max(start, t_start), ps);
        for (i64 p : ps) {
          if (!frontier_contains_version(f, p))
            f.insert(std::upper_bound(f.begin(), f.end(), p), p);
        }
      }
      if (start >= t_start) break;
      end = t_start;
      i--;
    }
  }
};

// ---------------------------------------------------------------- agents

struct AgentRun { i64 seq_start, seq_end, lv_start; };

struct Agents {
  std::vector<std::string> names;
  std::vector<std::vector<AgentRun>> client_runs;
  // global: (lv_start, lv_end, agent, seq_start), lv-sorted
  struct GRun { i64 lv0, lv1; i64 agent, seq0; };
  std::vector<GRun> global_runs;

  const GRun& find_global(i64 lv) const {
    size_t lo = 0, hi = global_runs.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (global_runs[mid].lv0 <= lv) lo = mid + 1; else hi = mid; }
    return global_runs[lo - 1];
  }

  void local_to_agent(i64 lv, i64& agent, i64& seq) const {
    const GRun& g = find_global(lv);
    agent = g.agent;
    seq = g.seq0 + (lv - g.lv0);
  }

  i64 span_len(i64 lv, i64 max_len) const {
    const GRun& g = find_global(lv);
    return std::min(g.lv1 - lv, max_len);
  }
};

// ---------------------------------------------------------------- op store

struct OpRun { i64 lv; u8 kind; u8 fwd; i64 start, end; i64 cp; };
static const u8 INS = 0, DEL = 1;

struct Ops {
  std::vector<OpRun> runs;

  size_t find_idx(i64 lv) const {
    size_t lo = 0, hi = runs.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (runs[mid].lv <= lv) lo = mid + 1; else hi = mid; }
    return lo - 1;
  }

  // sub-run covering item offsets [o0, o1) of run r
  static OpRun slice(const OpRun& r, i64 o0, i64 o1) {
    i64 n = r.end - r.start;
    if (o0 == 0 && o1 == n) return r;
    OpRun out = r;
    out.lv = r.lv + o0;
    if (r.cp >= 0) out.cp = r.cp + o0;
    i64 s, e;
    if (r.kind == INS) {
      s = r.start + o0; e = s + (o1 - o0);
    } else if (r.fwd) {
      s = r.start; e = s + (o1 - o0);
    } else {
      s = r.end - o1; e = r.end - o0;
    }
    out.start = s; out.end = e;
    return out;
  }
};

// ---------------------------------------------------------------- tracker

struct Node {
  i64 ids, ide, ol, orr;
  int32_t state;  // 0 NIY, 1 inserted, >=2 deleted (state-1) times
  bool ever;
  uint32_t prio;
  Node *l = nullptr, *r = nullptr, *p = nullptr;
  i64 s_len, s_cur, s_up;

  inline i64 n_len() const { return ide - ids; }
  inline i64 n_cur() const { return state == 1 ? ide - ids : 0; }
  inline i64 n_up() const { return ever ? 0 : ide - ids; }
  inline i64 origin_left_at(i64 off) const { return off == 0 ? ol : ids + off - 1; }
};

static inline void upd(Node* n) {
  i64 ln = 0, lc = 0, lu = 0, rn = 0, rc = 0, ru = 0;
  if (n->l) { ln = n->l->s_len; lc = n->l->s_cur; lu = n->l->s_up; }
  if (n->r) { rn = n->r->s_len; rc = n->r->s_cur; ru = n->r->s_up; }
  n->s_len = ln + rn + n->n_len();
  n->s_cur = lc + rc + n->n_cur();
  n->s_up = lu + ru + n->n_up();
}

static inline void fix_path(Node* n) { while (n) { upd(n); n = n->p; } }

// Propagate a (cur, up) delta from a node whose own contribution changed
// state (no structural change). Much cheaper than recomputing children.
static inline void bump_path(Node* n, i64 dcur, i64 dup) {
  while (n) { n->s_cur += dcur; n->s_up += dup; n = n->p; }
}

static inline void bump_path3(Node* n, i64 dlen, i64 dcur, i64 dup) {
  while (n) { n->s_len += dlen; n->s_cur += dcur; n->s_up += dup; n = n->p; }
}

static Node* leftmost(Node* n) { while (n->l) n = n->l; return n; }

static Node* succ(Node* n) {
  if (n->r) return leftmost(n->r);
  while (n->p && n == n->p->r) n = n->p;
  return n->p;
}

static Node* pred(Node* n) {
  if (n->l) { Node* x = n->l; while (x->r) x = x->r; return x; }
  while (n->p && n == n->p->l) n = n->p;
  return n->p;
}

struct Cursor { Node* node; i64 off; };  // node==nullptr => end of doc

struct DelRow { i64 lv0, lv1, t0, t1; bool fwd; };

struct Tracker {
  std::vector<Node*> pool;
  Node* root;
  // ins index: id_start -> node (covers underwater)
  std::map<i64, Node*> ins_index;
  std::map<i64, DelRow> del_rows;  // keyed by lv0
  uint64_t rng_state = 0x5EED5EED12345678ull;

  uint32_t next_prio() {
    rng_state ^= rng_state << 13; rng_state ^= rng_state >> 7;
    rng_state ^= rng_state << 17;
    return (uint32_t)rng_state;
  }

  Node* alloc(i64 ids, i64 ide, i64 ol, i64 orr, int32_t state, bool ever) {
    Node* n = new Node();
    n->ids = ids; n->ide = ide; n->ol = ol; n->orr = orr;
    n->state = state; n->ever = ever;
    n->prio = next_prio();
    upd(n);
    pool.push_back(n);
    return n;
  }

  Tracker() {
    root = alloc(UNDERWATER, UNDERWATER + (UNDERWATER - 1), ROOT, ROOT, 1, false);
    ins_index[root->ids] = root;
  }
  ~Tracker() { for (Node* n : pool) delete n; }

  void reg(Node* n) { ins_index[n->ids] = n; }

  Node* ins_lookup(i64 lv) const {
    auto it = ins_index.upper_bound(lv);
    --it;
    Node* n = it->second;
    assert(n->ids <= lv && lv < n->ide);
    return n;
  }

  // Remove a node from the treap (its items now belong to a neighbor).
  void erase_node(Node* n) {
    while (n->l || n->r) {
      Node* c = (!n->r || (n->l && n->l->prio < n->r->prio)) ? n->l : n->r;
      rot_up(c);
    }
    Node* p = n->p;
    if (p) {
      if (p->l == n) p->l = nullptr; else p->r = nullptr;
    } else {
      root = nullptr;  // callers guarantee this can't happen (underwater)
    }
    n->p = nullptr;
    bump_path3(p, -n->n_len(), -n->n_cur(), -n->n_up());
  }

  // RLE re-merge: if `n` is the linear continuation of its doc-order
  // predecessor (same conditions as the reference's YjsSpan::can_append,
  // yjsspan.rs:168-174), fold it in. Returns the surviving node.
  Node* try_merge_left(Node* n) {
    if (n->ol != n->ids - 1) return n;     // linear origin chain (cheap reject)
    Node* p = pred(n);
    if (!p) return n;
    if (p->ide != n->ids) return n;        // ids must be contiguous
    if (n->orr != p->orr) return n;
    if (n->state != p->state || n->ever != p->ever) return n;
    i64 dlen = n->n_len(), dcur = n->n_cur(), dup = n->n_up();
    erase_node(n);
    ins_index.erase(n->ids);
    p->ide = n->ide;
    bump_path3(p, dlen, dcur, dup);
    return p;
  }

  void rot_up(Node* x) {
    Node* p = x->p;
    Node* g = p->p;
    if (x == p->l) {
      p->l = x->r; if (x->r) x->r->p = p;
      x->r = p;
    } else {
      p->r = x->l; if (x->l) x->l->p = p;
      x->l = p;
    }
    p->p = x; x->p = g;
    if (g) { if (g->l == p) g->l = x; else g->r = x; }
    else root = x;
    upd(p); upd(x);
  }

  void insert_leaf(Node* x) {
    // x is attached with empty children: ancestors gain x's contribution.
    bump_path3(x->p, x->n_len(), x->n_cur(), x->n_up());
    while (x->p && x->prio < x->p->prio) rot_up(x);
  }

  void insert_after(Node* a, Node* x) {
    if (!a->r) { a->r = x; x->p = a; }
    else { Node* b = leftmost(a->r); b->l = x; x->p = b; }
    insert_leaf(x);
  }

  void insert_first(Node* x) {
    Node* b = leftmost(root);
    b->l = x; x->p = b;
    insert_leaf(x);
  }

  Node* split(Node* n, i64 off) {
    assert(0 < off && off < n->n_len());
    Node* rn = alloc(n->ids + off, n->ide, n->ids + off - 1, n->orr,
                     n->state, n->ever);
    n->ide = n->ids + off;
    // n's own contribution shrank by rn's size.
    bump_path3(n, -rn->n_len(), -rn->n_cur(), -rn->n_up());
    upd(n);  // local recompute for n itself (its children are unchanged)
    insert_after(n, rn);
    reg(rn);
    return rn;
  }

  i64 prefix(const Node* n, int which) const {
    auto sub = [&](const Node* x) -> i64 {
      if (!x) return 0;
      return which == 0 ? x->s_len : which == 1 ? x->s_cur : x->s_up;
    };
    auto own = [&](const Node* x) -> i64 {
      return which == 0 ? x->n_len() : which == 1 ? x->n_cur() : x->n_up();
    };
    i64 acc = sub(n->l);
    const Node* x = n;
    while (x->p) {
      if (x == x->p->r) acc += sub(x->p->l) + own(x->p);
      x = x->p;
    }
    return acc;
  }

  i64 raw_pos(Cursor c) const {
    if (!c.node) return root->s_len;
    return prefix(c.node, 0) + c.off;
  }

  i64 upstream_pos(Cursor c) const {
    if (!c.node) return root->s_up;
    return prefix(c.node, 2) + (c.node->ever ? 0 : c.off);
  }

  Cursor find_by_cur(i64 pos) const {
    Node* n = root;
    assert(pos < n->s_cur);
    while (true) {
      i64 lc = n->l ? n->l->s_cur : 0;
      if (pos < lc) { n = n->l; continue; }
      pos -= lc;
      i64 here = n->n_cur();
      if (pos < here) return {n, pos};
      pos -= here;
      n = n->r;
    }
  }

  // normalize so off < len; {nullptr,0} at end of doc
  bool roll(Cursor& c) const {
    if (!c.node) return false;
    while (c.off >= c.node->n_len()) {
      Node* nx = succ(c.node);
      if (!nx) { c.node = nullptr; c.off = 0; return false; }
      c.node = nx; c.off = 0;
    }
    return true;
  }

  Cursor cursor_before_item(i64 lv) const {
    if (lv == ROOT) return {nullptr, 0};  // end sentinel
    Node* n = ins_lookup(lv);
    return {n, lv - n->ids};
  }

  Cursor cursor_after_item(i64 lv) const {
    if (lv == ROOT) return {leftmost(root), 0};
    Node* n = ins_lookup(lv);
    Cursor c{n, lv - n->ids + 1};
    roll(c);
    return c;
  }

  int cmp_cursors(Cursor a, Cursor b) const {
    i64 pa = raw_pos(a), pb = raw_pos(b);
    return pa < pb ? -1 : pa > pb ? 1 : 0;
  }

  void insert_at(Cursor c, Node* node) {
    if (!c.node) {
      Node* x = root; while (x->r) x = x->r;
      insert_after(x, node);
    } else if (c.off == 0) {
      Node* pv = pred(c.node);
      if (!pv) insert_first(node);
      else insert_after(pv, node);
    } else if (c.off == c.node->n_len()) {
      insert_after(c.node, node);
    } else {
      split(c.node, c.off);
      insert_after(c.node, node);
    }
    reg(node);
  }

  i64 integrate(const Agents& aa, i64 agent, Node* item, Cursor cursor) {
    bool at_end = !roll(cursor);
    Cursor left_cursor = cursor;
    Cursor scan_start = cursor;
    bool scanning = false;

    while (!at_end && cursor.node) {
      if (!roll(cursor)) break;
      Node* other = cursor.node;
      i64 off = cursor.off;
      i64 other_lv = other->ids + off;
      if (other_lv == item->orr) break;
      assert(other->state == 0);

      i64 other_left_lv = other->origin_left_at(off);
      Cursor olc = cursor_after_item(other_left_lv);
      int c = cmp_cursors(olc, left_cursor);
      if (c < 0) break;
      if (c == 0) {
        if (item->orr == other->orr) {
          i64 oa, oseq;
          aa.local_to_agent(other_lv, oa, oseq);
          const std::string& my_name = aa.names[agent];
          const std::string& other_name = aa.names[oa];
          bool ins_here;
          if (my_name < other_name) ins_here = true;
          else if (my_name == other_name) {
            i64 ma, mseq;
            aa.local_to_agent(item->ids, ma, mseq);
            ins_here = mseq < oseq;
          } else ins_here = false;
          if (ins_here) break;
          scanning = false;
        } else {
          Cursor mr = cursor_before_item(item->orr);
          Cursor orc = cursor_before_item(other->orr);
          if (cmp_cursors(orc, mr) < 0) {
            if (!scanning) { scanning = true; scan_start = cursor; }
          } else scanning = false;
        }
      }
      Node* nx = succ(other);
      if (!nx) { cursor = {other, other->n_len()}; break; }
      cursor = {nx, 0};
    }
    if (scanning) cursor = scan_start;
    Cursor at = cursor.node ? cursor : Cursor{nullptr, 0};
    i64 pos = upstream_pos(at);
    insert_at(at, item);
    return pos;
  }

  // returns (consumed, xf_pos) — xf_pos = -1 => delete already happened
  std::pair<i64, i64> apply(const Agents& aa, i64 agent, const OpRun& op,
                            i64 max_len) {
    i64 length = std::min(max_len, op.end - op.start);
    if (op.kind == INS) {
      assert(op.fwd && "reverse insert runs unsupported");
      i64 origin_left;
      Cursor cursor;
      if (op.start == 0) {
        origin_left = ROOT;
        cursor = {leftmost(root), 0};
      } else {
        Cursor c = find_by_cur(op.start - 1);
        origin_left = c.node->ids + c.off;
        cursor = {c.node, c.off + 1};
      }
      // origin_right: next non-NIY item
      Cursor c2 = cursor;
      i64 origin_right = ROOT;
      if (roll(c2)) {
        while (true) {
          if (c2.node->state == 0) {
            Node* nx = succ(c2.node);
            if (!nx) { origin_right = ROOT; break; }
            c2 = {nx, 0};
          } else { origin_right = c2.node->ids + c2.off; break; }
        }
      }
      Node* item = alloc(op.lv, op.lv + length, origin_left, origin_right,
                         1, false);
      i64 pos = integrate(aa, agent, item, cursor);
      return {length, pos};
    } else {
      bool fwd = op.fwd;
      Cursor cursor;
      i64 take_req;
      if (fwd) {
        cursor = find_by_cur(op.start);
        take_req = length;
      } else {
        i64 last_pos = op.end - 1;
        Cursor c = find_by_cur(last_pos);
        i64 entry_start_pos = last_pos - c.off;
        i64 edit_start = std::max(entry_start_pos, op.end - length);
        take_req = op.end - edit_start;
        cursor = {c.node, c.off - (take_req - 1)};
      }
      Node* n = cursor.node;
      i64 off = cursor.off;
      assert(n->state == 1);
      bool ever_deleted = n->ever;
      i64 del_start_xf = upstream_pos(cursor);
      i64 take = std::min(take_req, n->n_len() - off);
      if (off > 0) n = split(n, off);
      if (take < n->n_len()) split(n, take);
      i64 t0 = n->ids, t1 = n->ide;
      i64 dcur = n->state == 1 ? -(t1 - t0) : 0;
      i64 dup = n->ever ? 0 : -(t1 - t0);
      n->state += 1;
      n->ever = true;
      bump_path(n, dcur, dup);

      del_rows[op.lv] = DelRow{op.lv, op.lv + take, t0, t1, fwd};
      return {take, ever_deleted ? -1 : del_start_xf};
    }
  }

  // ---- advance / retreat ----

  struct QueryRes { u8 kind; i64 t0, t1; bool fwd; i64 offset, total; };

  QueryRes index_query(i64 lv) const {
    auto it = del_rows.upper_bound(lv);
    if (it != del_rows.begin()) {
      const DelRow& r = std::prev(it)->second;
      if (r.lv0 <= lv && lv < r.lv1)
        return {DEL, r.t0, r.t1, r.fwd, lv - r.lv0, r.lv1 - r.lv0};
    }
    Node* n = ins_lookup(lv);
    return {INS, n->ids, n->ide, true, lv - n->ids, n->n_len()};
  }

  static void rr_sub(i64 t0, i64 t1, bool fwd, i64 o0, i64 o1,
                     i64& lo, i64& hi) {
    if (fwd) { lo = t0 + o0; hi = t0 + o1; }
    else { lo = t1 - o1; hi = t1 - o0; }
  }

  void toggle_items(i64 s, i64 e, int mode) {
    // modes: 0 ins, 1 unins, 2 del, 3 undel
    i64 lv = s;
    while (lv < e) {
      Node* n = ins_lookup(lv);
      if (lv > n->ids) n = split(n, lv - n->ids);
      if (e < n->ide) split(n, e - n->ids);
      i64 len = n->n_len();
      i64 dcur = 0, dup = 0;
      switch (mode) {
        case 0: assert(n->state == 0); n->state = 1; dcur = len; break;
        case 1: assert(n->state == 1); n->state = 0; dcur = -len; break;
        case 2:
          assert(n->state >= 1);
          if (n->state == 1) dcur = -len;
          n->state += 1;
          if (!n->ever) { dup = -len; n->ever = true; }
          break;
        case 3:
          assert(n->state >= 2);
          n->state -= 1;
          if (n->state == 1) dcur = len;
          break;
      }
      bump_path(n, dcur, dup);
      lv = n->ide;
      try_merge_left(n);
    }
  }

  void advance_by_range(Span rng) {
    i64 start = rng.start, end = rng.end;
    while (start < end) {
      QueryRes q = index_query(start);
      i64 take = std::min(q.total - q.offset, end - start);
      i64 lo, hi;
      rr_sub(q.t0, q.t1, q.fwd, q.offset, q.offset + take, lo, hi);
      toggle_items(lo, hi, q.kind == INS ? 0 : 2);
      start += take;
    }
  }

  void retreat_by_range(Span rng) {
    i64 start = rng.start, end = rng.end;
    while (start < end) {
      i64 req = end - 1;
      QueryRes q = index_query(req);
      i64 chunk_start = req - q.offset;
      i64 s = std::max(start, chunk_start);
      i64 e = std::min(end, chunk_start + q.total);
      i64 o0 = s - chunk_start;
      i64 lo, hi;
      rr_sub(q.t0, q.t1, q.fwd, o0, o0 + (e - s), lo, hi);
      toggle_items(lo, hi, q.kind == INS ? 1 : 3);
      end -= e - s;
    }
  }
};

// ---------------------------------------------------------------- walker

struct VisitEntry {
  Span span;
  std::vector<i64> parents;
  std::vector<int> parent_idxs, child_idxs;
  bool visited = false;
};

struct Walker {
  const Graph& g;
  std::vector<i64> frontier;
  std::vector<VisitEntry> input;
  std::vector<int> to_process;

  Walker(const Graph& graph, const std::vector<Span>& rev_spans,
         std::vector<i64> start_at)
      : g(graph), frontier(std::move(start_at)) {
    auto find_entry_idx = [&](i64 t) -> int {
      int lo = 0, hi = (int)input.size();
      while (lo < hi) {
        int mid = (lo + hi) / 2;
        if (t < input[mid].span.start) hi = mid;
        else if (t >= input[mid].span.end) lo = mid + 1;
        else return mid;
      }
      return -1;
    };
    for (auto it = rev_spans.rbegin(); it != rev_spans.rend(); ++it) {
      i64 start = it->start, end = it->end;
      size_t i = g.find_idx(start);
      while (start < end) {
        i64 t_end = std::min(g.ends[i], end);
        VisitEntry e;
        e.span = {start, t_end};
        g.parents_at(start, e.parents);
        for (i64 p : e.parents) {
          int pi = find_entry_idx(p);
          if (pi >= 0) e.parent_idxs.push_back(pi);
        }
        if (e.parent_idxs.empty()) to_process.push_back((int)input.size());
        input.push_back(std::move(e));
        start = t_end;
        i++;
      }
    }
    for (int i = 0; i < (int)input.size(); i++)
      for (int p : input[i].parent_idxs) input[p].child_idxs.push_back(i);
    std::reverse(to_process.begin(), to_process.end());
  }

  // returns false when done
  bool next(std::vector<Span>& retreat, std::vector<Span>& advance_rev,
            Span& consume) {
    if (to_process.empty()) return false;
    int idx = to_process.back();
    if (input[idx].parents.size() >= 2) {
      int found = -1;
      for (int ii = (int)to_process.size() - 1; ii >= 0; ii--) {
        if (input[to_process[ii]].parents.size() < 2) { found = ii; break; }
      }
      if (found >= 0) {
        idx = to_process[found];
        to_process[found] = to_process.back();
        to_process.pop_back();
      } else to_process.pop_back();
    } else to_process.pop_back();

    VisitEntry& e = input[idx];
    e.visited = true;

    g.diff_rev(frontier, e.parents, retreat, advance_rev);
    for (const Span& s : retreat) g.retreat(frontier, s);
    for (auto it = advance_rev.rbegin(); it != advance_rev.rend(); ++it)
      g.advance(frontier, *it);
    g.advance_known_run(frontier, e.parents, e.span);

    for (int c : e.child_idxs) {
      if (input[c].visited) continue;
      bool ok = true;
      for (int p : input[c].parent_idxs)
        if (!input[p].visited) { ok = false; break; }
      if (ok) to_process.push_back(c);
    }
    consume = e.span;
    return true;
  }
};

// ---------------------------------------------------------------- context

struct XfOp { i64 lv; i64 len; u8 kind; u8 fwd; i64 pos; };  // pos=-1 => gone

// Chunked int32 text buffer (the native rope; mirrors
// diamond_types_tpu/utils/rope.py).
struct TextBuf {
  static const size_t TARGET = 2048;
  std::vector<std::vector<int32_t>> chunks;
  std::vector<i64> cum;  // chars before chunk i; size chunks.size()+1
  bool dirty = true;
  i64 total = 0;

  TextBuf() { chunks.emplace_back(); }

  void rebuild() {
    cum.resize(chunks.size() + 1);
    cum[0] = 0;
    for (size_t i = 0; i < chunks.size(); i++)
      cum[i + 1] = cum[i] + (i64)chunks[i].size();
    dirty = false;
  }

  std::pair<size_t, i64> find(i64 pos) {
    if (dirty) rebuild();
    size_t lo = 0, hi = chunks.size();
    while (lo < hi) { size_t mid = (lo + hi) / 2;
      if (cum[mid + 1] <= pos) lo = mid + 1; else hi = mid; }
    if (lo >= chunks.size()) { lo = chunks.size() - 1; }
    return {lo, pos - cum[lo]};
  }

  void insert(i64 pos, const int32_t* s, i64 n) {
    if (n <= 0) return;
    auto [ci, off] = find(pos);
    auto& ch = chunks[ci];
    ch.insert(ch.begin() + off, s, s + n);
    total += n;
    if (ch.size() > 2 * TARGET) {
      // split into TARGET-sized chunks
      std::vector<std::vector<int32_t>> parts;
      for (size_t i = 0; i < ch.size(); i += TARGET)
        parts.emplace_back(ch.begin() + i,
                           ch.begin() + std::min(ch.size(), i + TARGET));
      chunks.erase(chunks.begin() + ci);
      chunks.insert(chunks.begin() + ci, parts.begin(), parts.end());
    }
    dirty = true;
  }

  void erase(i64 pos, i64 n) {
    if (n <= 0) return;
    total -= n;
    auto [ci, off] = find(pos);
    while (n > 0) {
      auto& ch = chunks[ci];
      i64 take = std::min((i64)ch.size() - off, n);
      ch.erase(ch.begin() + off, ch.begin() + off + take);
      n -= take;
      if (ch.empty() && chunks.size() > 1) chunks.erase(chunks.begin() + ci);
      else ci++;
      off = 0;
    }
    dirty = true;
  }

  void dump(int32_t* out) const {
    i64 k = 0;
    for (const auto& ch : chunks) {
      std::memcpy(out + k, ch.data(), ch.size() * sizeof(int32_t));
      k += ch.size();
    }
  }
};

struct Ctx {
  Graph g;
  Agents aa;
  Ops ops;
  std::vector<int32_t> ins_arena;
  TextBuf doc;
  std::vector<i64> version;
  std::vector<XfOp> out;
  std::vector<i64> out_frontier;
};

static void emit_ops_range(Ctx* c, Tracker& tracker, Span consume,
                           bool emit) {
  Ops& ops = c->ops;
  if (span_empty(consume)) return;
  size_t i = ops.find_idx(consume.start);
  i64 pos = consume.start;
  while (pos < consume.end) {
    const OpRun& run = ops.runs[i];
    i64 run_end = run.lv + (run.end - run.start);
    i64 o0 = pos - run.lv;
    i64 o1 = std::min(consume.end, run_end) - run.lv;
    OpRun piece = Ops::slice(run, o0, o1);
    // apply in chunks bounded by agent runs
    while (true) {
      i64 plen = piece.end - piece.start;
      i64 agent, seq;
      c->aa.local_to_agent(piece.lv, agent, seq);
      i64 alen = c->aa.span_len(piece.lv, plen);
      auto [consumed, xf] = tracker.apply(c->aa, agent, piece, alen);
      if (emit)
        c->out.push_back({piece.lv, consumed, piece.kind, piece.fwd, xf});
      if (consumed == plen) break;
      piece = Ops::slice(piece, consumed, plen);
    }
    pos = run.lv + o1;
    i++;
  }
}

static void transform(Ctx* c, std::vector<i64> from, std::vector<i64> merge) {
  c->out.clear();
  std::vector<Span> new_ops, conflict_ops;
  std::vector<i64> common = c->g.find_conflicting(
      from, merge, [&](Span s, u8 flag) {
        push_reversed_rle(flag == Graph::OnlyB ? new_ops : conflict_ops, s);
      });

  std::vector<i64> next_frontier = from;
  bool did_ff = false;

  // FF mode
  std::vector<i64> ps;
  while (!new_ops.empty()) {
    Span span = new_ops.back();
    size_t i = c->g.find_idx(span.start);
    c->g.parents_at(span.start, ps);
    if (ps != next_frontier) break;
    new_ops.pop_back();
    i64 take_end = std::min(c->g.ends[i], span.end);
    if (take_end < span.end) new_ops.push_back({take_end, span.end});
    next_frontier.assign(1, take_end - 1);
    did_ff = true;
    // emit untransformed
    Ops& ops = c->ops;
    size_t oi = ops.find_idx(span.start);
    i64 pos = span.start;
    while (pos < take_end) {
      const OpRun& run = ops.runs[oi];
      i64 run_end = run.lv + (run.end - run.start);
      i64 o1 = std::min(take_end, run_end) - run.lv;
      OpRun piece = Ops::slice(run, pos - run.lv, o1);
      c->out.push_back({piece.lv, piece.end - piece.start, piece.kind,
                        piece.fwd, piece.start});
      pos = run.lv + o1;
      oi++;
    }
  }

  if (!new_ops.empty()) {
    if (did_ff) {
      conflict_ops.clear();
      common = c->g.find_conflicting(
          next_frontier, merge, [&](Span s, u8 flag) {
            if (flag != Graph::OnlyB) push_reversed_rle(conflict_ops, s);
          });
    }

    Tracker tracker;
    // build tracker over conflict set
    {
      Walker w(c->g, conflict_ops, common);
      std::vector<Span> retreat, advance_rev;
      Span consume;
      while (w.next(retreat, advance_rev, consume)) {
        for (const Span& s : retreat) tracker.retreat_by_range(s);
        for (auto it = advance_rev.rbegin(); it != advance_rev.rend(); ++it)
          tracker.advance_by_range(*it);
        emit_ops_range(c, tracker, consume, false);
      }
      // walk new ops
      Walker w2(c->g, new_ops, w.frontier);
      while (w2.next(retreat, advance_rev, consume)) {
        for (const Span& s : retreat) tracker.retreat_by_range(s);
        for (auto it = advance_rev.rbegin(); it != advance_rev.rend(); ++it)
          tracker.advance_by_range(*it);
        c->g.advance(next_frontier, consume);
        emit_ops_range(c, tracker, consume, true);
      }
    }
  }
  c->out_frontier = next_frontier;
}

// ---------------------------------------------------------------- C ABI

extern "C" {

void* dt_ctx_new() { return new Ctx(); }
void dt_ctx_free(void* p) { delete (Ctx*)p; }

void dt_add_agent(void* p, const char* name) {
  Ctx* c = (Ctx*)p;
  c->aa.names.emplace_back(name);
  c->aa.client_runs.emplace_back();
}

// bulk loads (columnar)
void dt_load_graph(void* p, i64 n, const i64* starts, const i64* ends,
                   const i64* shadows, const i64* pindptr, const i64* pflat) {
  Ctx* c = (Ctx*)p;
  c->g.starts.assign(starts, starts + n);
  c->g.ends.assign(ends, ends + n);
  c->g.shadows.assign(shadows, shadows + n);
  c->g.parents.resize(n);
  for (i64 i = 0; i < n; i++)
    c->g.parents[i].assign(pflat + pindptr[i], pflat + pindptr[i + 1]);
}

void dt_load_agent_runs(void* p, i64 n, const i64* lv0, const i64* lv1,
                        const i64* agent, const i64* seq0) {
  Ctx* c = (Ctx*)p;
  c->aa.global_runs.clear();
  for (i64 i = 0; i < n; i++) {
    c->aa.global_runs.push_back({lv0[i], lv1[i], agent[i], seq0[i]});
    c->aa.client_runs[agent[i]].push_back(
        {seq0[i], seq0[i] + (lv1[i] - lv0[i]), lv0[i]});
  }
  for (auto& runs : c->aa.client_runs)
    std::sort(runs.begin(), runs.end(),
              [](const AgentRun& a, const AgentRun& b) {
                return a.seq_start < b.seq_start;
              });
}

void dt_load_ops(void* p, i64 n, const i64* lv, const u8* kind,
                 const u8* fwd, const i64* start, const i64* end,
                 const i64* cp) {
  Ctx* c = (Ctx*)p;
  c->ops.runs.clear();
  c->ops.runs.reserve(n);
  for (i64 i = 0; i < n; i++)
    c->ops.runs.push_back({lv[i], kind[i], fwd[i], start[i], end[i], cp[i]});
}

void dt_load_ins_arena(void* p, i64 n, const int32_t* chars) {
  Ctx* c = (Ctx*)p;
  c->ins_arena.assign(chars, chars + n);
}

// transform: fills internal out buffer; returns count
i64 dt_transform(void* p, const i64* from, i64 nf, const i64* merge, i64 nm) {
  Ctx* c = (Ctx*)p;
  transform(c, std::vector<i64>(from, from + nf),
            std::vector<i64>(merge, merge + nm));
  return (i64)c->out.size();
}

// Full native merge: transform + materialize into the ctx's doc buffer.
// init (may be null/0) seeds the document. Returns final doc length.
i64 dt_merge_into_doc(void* p, const int32_t* init, i64 init_len,
                      const i64* from, i64 nf, const i64* merge, i64 nm) {
  Ctx* c = (Ctx*)p;
  c->doc = TextBuf();
  if (init_len > 0) c->doc.insert(0, init, init_len);
  transform(c, std::vector<i64>(from, from + nf),
            std::vector<i64>(merge, merge + nm));
  for (const XfOp& x : c->out) {
    if (x.pos < 0) continue;
    if (x.kind == INS) {
      // content chars for [lv, lv+len): arena offset via the op run's cp
      const OpRun& run = c->ops.runs[c->ops.find_idx(x.lv)];
      i64 cp = run.cp + (x.lv - run.lv);
      c->doc.insert(x.pos, c->ins_arena.data() + cp, x.len);
    } else {
      c->doc.erase(x.pos, x.len);
    }
  }
  return c->doc.total;
}

void dt_get_doc(void* p, int32_t* out) { ((Ctx*)p)->doc.dump(out); }

void dt_get_out(void* p, i64* lv, i64* len, u8* kind, u8* fwd, i64* pos) {
  Ctx* c = (Ctx*)p;
  for (size_t i = 0; i < c->out.size(); i++) {
    lv[i] = c->out[i].lv;
    len[i] = c->out[i].len;
    kind[i] = c->out[i].kind;
    fwd[i] = c->out[i].fwd;
    pos[i] = c->out[i].pos;
  }
}

i64 dt_get_out_frontier(void* p, i64* buf, i64 cap) {
  Ctx* c = (Ctx*)p;
  i64 n = std::min((i64)c->out_frontier.size(), cap);
  for (i64 i = 0; i < n; i++) buf[i] = c->out_frontier[i];
  return (i64)c->out_frontier.size();
}

}  // extern "C"
