#!/usr/bin/env python3
"""Tunnel-recovery watcher (VERDICT r4 next-step #2).

The TPU tunnel on this machine has been wedged for three consecutive
rounds; the judge's standing ask is to bank on-chip numbers in ANY
window the hardware allows, with per-probe liveness evidence when it
does not. This watcher runs all round in the background:

  * every PROBE_INTERVAL_S it runs bench.device_probe() (subprocess,
    watchdog-bounded — a wedged backend costs ~90 s per attempt, never
    a hang) and appends one JSON line per attempt to DEVICE_WATCH.jsonl:
    the documented per-probe liveness log.
  * on a live probe (and while the bank is not yet complete) it runs
    the full device phase (bench._run_device_phase, reusing the fresh
    probe result — no second probe round-trip) with
    DT_DEVICE_PARTIAL_PATH pointed at a per-run scratch file, then
    MERGES that run's summary into DEVICE_BANK.json bench-by-bench: a
    later ok result replaces an earlier error, an earlier ok result is
    never clobbered by a later error or by the empty summary a fresh
    phase starts with. The merge runs in a `finally`, so a phase crash
    still banks whatever individual benches completed before it.

Run detached:  nohup python device_watcher.py >/tmp/watcher.out 2>&1 &
Stop:          touch /root/repo/.stop_watcher
When relaunching after a stop, wait for the old process to exit first
(the single-instance guard defers to a still-draining watcher).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
import bench  # noqa: E402

WATCH_LOG = os.path.join(REPO, "DEVICE_WATCH.jsonl")
BANK = os.path.join(REPO, "DEVICE_BANK.json")
RUN_SCRATCH = os.path.join(REPO, ".device_run.json")
STOP = os.path.join(REPO, ".stop_watcher")
PIDFILE = os.path.join(REPO, ".watcher_pid")
PROBE_INTERVAL_S = 15 * 60

# Single source of truth for the bench list lives in bench.py next to
# the phase that emits the keys; ok keys are mapped by _bench_of below
# (several benches emit ok keys that do NOT share the bench's prefix).
BENCHES = bench.DEVICE_BENCHES


def _bench_of(key: str):
    """Map a summary key to the bench that owns it (None = global key
    like device_platform / tunnel_rtt_ms, merged by plain overwrite)."""
    if key.endswith("_error"):
        base = key[: -len("_error")]
        return base if base in BENCHES else None
    # ok keys with non-prefix names (see bench._run_device_phase):
    if key.startswith("tpu_merge_node_nodecc_best") or \
            key == "tpu_merge_batch_sweep":
        return "tpu_merge_node_nodecc_sweep"
    if key.startswith("tpu_session"):
        return "tpu_session_friendsforever"
    if key.startswith("tpu_transform"):
        return "tpu_transform_git_makefile"
    for b in BENCHES:
        if key.startswith(b):
            return b
    return None


def _log(entry: dict) -> None:
    entry["ts"] = time.time()
    entry["iso"] = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime())
    with open(WATCH_LOG, "a") as f:
        f.write(json.dumps(entry, default=str) + "\n")


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _group(summary: dict):
    """Split a summary into {bench: {key: val}} + {global key: val}."""
    per, glob = {b: {} for b in BENCHES}, {}
    for k, v in summary.items():
        b = _bench_of(k)
        if b is None:
            glob[k] = v
        else:
            per[b][k] = v
    return per, glob


def _bench_ok(keys: dict) -> bool:
    return any(not k.endswith("_error") for k in keys)


def _bench_full_ok(keys: dict) -> bool:
    """Ok data from a run that COMPLETED (no `_partial` marker — a sweep
    that timed out / crashed mid-curve banks its points but stays
    retryable)."""
    return _bench_ok(keys) and not any(k.endswith("_partial")
                                       for k in keys)


def _merge_summary(old: dict, new: dict) -> dict:
    """Bench-level merge that can only improve the bank: full-ok data is
    terminal; partial-ok data (timeout/crash mid-run, `_partial` marker)
    replaces errors and older partials but never full-ok data; a new
    error lands only if the bank has no ok data for that bench; global
    keys (platform, RTT) are overwritten."""
    old_per, old_glob = _group(old)
    new_per, new_glob = _group(new)
    merged = {}
    for b in BENCHES:
        if _bench_full_ok(old_per[b]):
            take = old_per[b] if not _bench_full_ok(new_per[b]) \
                else new_per[b]       # both full: later window wins
            merged.update(take)
        elif _bench_ok(new_per[b]):
            merged.update(new_per[b])
        elif _bench_ok(old_per[b]):
            merged.update(old_per[b])
        else:
            merged.update(old_per[b])
            merged.update(new_per[b])   # error keys only
    merged.update(old_glob)
    merged.update(new_glob)
    return merged


def _catch_complete(summary: dict) -> bool:
    """Complete = every device bench has banked ok data from a COMPLETED
    run (partial sweeps keep the bench on the retry list)."""
    per, _ = _group(summary)
    return all(_bench_full_ok(per[b]) for b in BENCHES)


def _bank_run(run_label: str, summary: dict = None,
              full: dict = None) -> dict:
    """Merge one phase run into the bank (atomic rename). The caller
    passes the phase's return value directly when it has one; the
    scratch file (written per-bench by bench._flush_partial, whose own
    write errors are silent) is only the crash fallback."""
    if summary is None:
        run = _read_json(RUN_SCRATCH)
        summary, full = run.get("summary", {}), run.get("full", {})
    bank = _read_json(BANK)
    merged = _merge_summary(bank.get("summary", {}), summary)
    bank["summary"] = merged
    runs = bank.setdefault("runs", [])
    contributed = any(_bench_of(k) is not None and not k.endswith("_error")
                      for k in summary)
    runs.append({"label": run_label, "at": time.time(), "summary": summary,
                 # full per-bench reports only for runs that produced
                 # data; error-only attempts are already in the probe log
                 **({"full": full} if contributed and full else {})})
    del runs[:-12]           # bound the bank on a flaky tunnel
    tmp = BANK + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bank, f, indent=1, default=str)
    os.replace(tmp, BANK)
    return merged


_pid_alive = bench._pid_alive


def _sleep_cycle() -> None:
    """Wait out one probe interval, reacting to the stop file within
    seconds (shared by the skip branch and the end-of-cycle wait)."""
    deadline = time.time() + PROBE_INTERVAL_S
    while time.time() < deadline and not os.path.exists(STOP):
        time.sleep(10)


def main() -> None:
    # single-instance guard: two watchers would race the bank's
    # read-modify-write and could lose a banked catch
    try:
        other = int(open(PIDFILE).read().strip())
        # bench._pid_is guards against PID reuse: only defer to a live
        # process that is actually a watcher
        if other != os.getpid() and bench._pid_is(other, b"device_watcher"):
            print(f"watcher already running (pid {other}); exiting")
            return
    except (OSError, ValueError):
        pass
    with open(PIDFILE, "w") as f:
        f.write(str(os.getpid()))
    try:
        os.remove(STOP)      # a stale stop request must not no-op a
    except OSError:          # freshly launched watcher
        pass

    _log({"event": "watcher_start", "pid": os.getpid(),
          "interval_s": PROBE_INTERVAL_S})
    while not os.path.exists(STOP):
        # sit a cycle out while an official bench run is in flight (its
        # host phase would bill our probe subprocess's CPU as slowdown)
        # or while another process holds the device lock mid-phase
        try:
            holder = int(open(bench.DEVICE_LOCK).read().strip() or "0")
        except (OSError, ValueError):
            holder = 0
        bench_active = bench.bench_is_active()
        if bench_active or \
                (holder and holder != os.getpid() and _pid_alive(holder)):
            _log({"event": "probe_skipped",
                  "why": "bench.py run in flight" if bench_active
                         else f"device lock held by pid {holder}"})
            _sleep_cycle()
            continue
        t0 = time.time()
        # probe under the device lock: the probe itself drives the
        # tunnel, so it must not land mid-bench of another process's
        # device phase (released before the phase, which re-acquires)
        bench._acquire_device_lock()
        try:
            probe = bench.device_probe()
        finally:
            bench._release_device_lock()
        _log({"event": "probe", "ok": bool(probe.get("ok")),
              "why": probe.get("why"), "rtt_ms": probe.get("rtt_ms"),
              "platform": probe.get("platform"),
              "probe_s": round(time.time() - t0, 1)})
        banked = _read_json(BANK).get("summary", {})
        if probe.get("ok") and bench.bench_is_active():
            # an official run started during our probe; its device phase
            # will bank this window's evidence itself — stand down so
            # our multi-minute phase can't overlap its host timings
            _log({"event": "phase_skipped",
                  "why": "bench.py started during probe"})
        elif probe.get("ok") and not _catch_complete(banked):
            _log({"event": "phase_start"})
            os.environ["DT_DEVICE_PARTIAL_PATH"] = RUN_SCRATCH
            try:
                os.remove(RUN_SCRATCH)
            except OSError:
                pass
            label = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime())
            # spend the window on what's missing: benches with banked
            # COMPLETE ok data are skipped inside the phase (their skip
            # errors are discarded by the bank merge; partial catches
            # stay on the retry list)
            per, _g = _group(banked)
            already = frozenset(b for b in BENCHES if _bench_full_ok(per[b]))
            phase_full, phase_out = {}, None
            try:
                phase_out = bench._run_device_phase(phase_full, probe=probe,
                                                    skip=already)
            except Exception as e:  # pragma: no cover
                _log({"event": "phase_crash", "error": repr(e)[:300]})
            finally:
                try:
                    merged = _bank_run(label, phase_out, phase_full)
                    _log({"event": "phase_banked",
                          "ok_keys": sorted(k for k in merged
                                            if not k.endswith("_error")),
                          "errors": {k: str(v)[:80]
                                     for k, v in merged.items()
                                     if k.endswith("_error")},
                          "complete": _catch_complete(merged)})
                except Exception as e:  # pragma: no cover — the watcher
                    # must keep probing even if banking itself fails
                    _log({"event": "bank_fail", "error": repr(e)[:300]})
        _sleep_cycle()
    _log({"event": "watcher_stop"})
    try:
        os.remove(PIDFILE)   # a dead pid must not lock out a relaunch
    except OSError:          # after pid reuse
        pass


if __name__ == "__main__":
    main()
